"""Model fleet plane (shifu_tpu/registry + shifu_tpu/serve/fleet).

Four contracts:

- REGISTRY ATOMICITY: publish commits an immutable version dir, then
  the HEAD pointer, via two atomic renames; a fault or SIGKILL at
  either `registry.publish` point leaves the previous HEAD intact and
  the registry readable, and a clean rerun succeeds. gc keeps the
  last K versions and never the HEAD; rollback is one HEAD commit.
- ROUTING PARITY: a score routed through `FleetService` bit-matches a
  standalone `ScorerService` on the same registry version dir — the
  fleet layer adds residency and admission, never arithmetic.
- RESIDENCY: under an HBM budget smaller than the fleet, the
  least-recently-used model is evicted and transparently re-warmed on
  its next hit, with identical scores across the round trip.
- ADMISSION + AUTOTUNING: when the rolling high-priority p99 breaches
  the SLO, low-priority submits shed (`ShedReject` → HTTP 429 +
  Retry-After) while high-priority traffic keeps flowing, and the
  hysteresis releases once the p99 recovers; the SLO autotuner halves
  / grows each model's admission deadline from its own metrics-store
  history and converges (no-op) inside the band.
"""

import json
import os
import queue
import signal
import subprocess
import sys
import textwrap
import urllib.error
import urllib.request

import numpy as np
import pytest

from shifu_tpu import registry, resilience
from tests.test_serve import _tiny_nn_dir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LADDER = (1, 4)   # two tiny buckets keep warms cheap in tier-1


@pytest.fixture(autouse=True)
def _no_faults():
    resilience.reset_faults()
    yield
    resilience.reset_faults()


def _publish(reg, name, tmp_path, seed=0, priority="high",
             ladder=LADDER, **kw):
    src = str(tmp_path / f"src_{name}_{seed}")
    _tiny_nn_dir(src, seed=seed)
    return registry.publish(reg, name, src, priority=priority,
                            ladder=ladder, **kw)


def _no_tmp_residue(root):
    stranded = []
    for dirpath, dirs, files in os.walk(root):
        stranded += [os.path.join(dirpath, e)
                     for e in list(dirs) + list(files)
                     if e.startswith(".tmp.")]
    return stranded


def _budget_mb_fitting(reg, names, fit):
    """An HBM budget that fits exactly `fit` of these (identically
    sized) models, with half a model of slack."""
    per = []
    for n in names:
        m = registry.read_manifest(reg, n)
        per.append(m["param_bytes"]
                   + m["ladder"][-1] * m["working_row_bytes"])
    return (sum(sorted(per)[:fit]) + min(per) / 2.0) / float(1 << 20)


# ---------------------------------------------------------------------------
# registry: publish / rollback / gc
# ---------------------------------------------------------------------------

def test_publish_creates_versions_and_flips_head(tmp_path):
    reg = str(tmp_path / "reg")
    assert _publish(reg, "a", tmp_path, seed=0) == "v001"
    assert _publish(reg, "a", tmp_path, seed=1,
                    priority="low", max_delay_ms=3.5) == "v002"
    assert registry.versions(reg, "a") == ["v001", "v002"]
    assert registry.head(reg, "a") == "v002"
    v, vdir, manifest = registry.resolve(reg, "a")
    assert v == "v002" and os.path.isdir(vdir)
    assert manifest["family"] == ["nn"]
    assert manifest["priority"] == "low"
    assert manifest["max_delay_ms"] == 3.5
    assert tuple(manifest["ladder"]) == LADDER
    assert manifest["param_bytes"] > 0
    assert manifest["input_dim"] == 12
    assert set(manifest["files"]) == {"model0.npz"}
    assert all(len(sha) == 64 for sha in manifest["files"].values())
    rows = registry.ls(reg)
    assert [r["name"] for r in rows] == ["a"]
    assert rows[0]["head"] == "v002"
    assert not _no_tmp_residue(reg)


def test_rollback_and_gc_keep_head(tmp_path):
    reg = str(tmp_path / "reg")
    for seed in range(3):
        _publish(reg, "a", tmp_path, seed=seed)
    assert registry.rollback(reg, "a") == "v002"
    assert registry.head(reg, "a") == "v002"
    # keep=1 would keep only the newest, but HEAD (v002) is pinned
    removed = registry.gc(reg, "a", keep=1)
    assert removed == ["v001"]
    assert registry.versions(reg, "a") == ["v002", "v003"]
    assert registry.head(reg, "a") == "v002"
    # roll forward is just another rollback
    assert registry.rollback(reg, "a", to="v003") == "v003"
    with pytest.raises(FileNotFoundError):
        registry.rollback(reg, "a", to="v999")
    assert not _no_tmp_residue(reg)


@pytest.mark.parametrize("nth", [1, 2])
def test_publish_fault_leaves_previous_head_intact(
        tmp_path, monkeypatch, nth):
    """`registry.publish` fires before EACH of the two commit renames;
    an injected fault at either leaves HEAD on the previous version
    and the registry fully readable, and a clean rerun succeeds."""
    reg = str(tmp_path / "reg")
    _publish(reg, "a", tmp_path, seed=0)
    monkeypatch.setenv("SHIFU_TPU_FAULT", f"registry.publish:oserror:{nth}")
    resilience.reset_faults()
    with pytest.raises(OSError,
                       match="injected oserror at registry.publish"):
        _publish(reg, "a", tmp_path, seed=1)
    assert registry.head(reg, "a") == "v001"
    assert registry.resolve(reg, "a")[0] == "v001"
    monkeypatch.delenv("SHIFU_TPU_FAULT")
    resilience.reset_faults()
    v = _publish(reg, "a", tmp_path, seed=1)
    assert registry.head(reg, "a") == v
    assert not _no_tmp_residue(reg)


_KILL_DRILL = textwrap.dedent("""\
    import sys
    from shifu_tpu import registry
    from tests.test_serve import _tiny_nn_dir
    reg, src, nth = sys.argv[1], sys.argv[2], sys.argv[3]
    registry.publish(reg, "a", src, ladder=(1, 4))   # v001, 2 sites
    import os
    os.environ["SHIFU_TPU_FAULT"] = "registry.publish:kill:" + nth
    registry.publish(reg, "a", src, ladder=(1, 4))   # killed mid-commit
    print("UNREACHABLE")
""")


@pytest.mark.parametrize("nth", [1, 2])
def test_sigkill_mid_publish_previous_head_survives(tmp_path, nth):
    """SIGKILL at either commit point of the SECOND publish (the fault
    env goes live after the first, so its calls are nth 1-2): HEAD
    must still name v001, resolve() must return the intact v001, and
    a rerun publish must recover — including scrubbing any stage dir
    the kill stranded."""
    reg = str(tmp_path / "reg")
    src = _tiny_nn_dir(str(tmp_path / "src"))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    r = subprocess.run(
        [sys.executable, "-c", _KILL_DRILL, reg, src, str(nth)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stdout,
                                             r.stderr)
    assert "UNREACHABLE" not in r.stdout
    assert registry.head(reg, "a") == "v001"
    v, vdir, manifest = registry.resolve(reg, "a")
    assert v == "v001" and manifest["name"] == "a"
    # recoverable: the next publish scrubs stage residue and commits
    assert registry.publish(reg, "a", src, ladder=LADDER) \
        not in (None, "v001")
    assert registry.head(reg, "a") != "v001"
    assert not _no_tmp_residue(reg)


# ---------------------------------------------------------------------------
# fleet: routing parity + LRU residency
# ---------------------------------------------------------------------------

def test_fleet_routes_bitwise_equal_to_standalone(tmp_path):
    from shifu_tpu.serve.fleet import FleetService
    from shifu_tpu.serve.service import ScorerService

    reg = str(tmp_path / "reg")
    _publish(reg, "a", tmp_path, seed=0)
    _publish(reg, "b", tmp_path, seed=1)
    x = np.random.default_rng(3).normal(0, 1, (3, 12)) \
        .astype(np.float32)
    with FleetService(reg, workspace_root=str(tmp_path),
                      hbm_budget_mb=0) as fleet:
        got_a = np.asarray(fleet.submit("a", dense=x)["mean"])
        got_b = np.asarray(fleet.submit("b", dense=x)["mean"])
        with pytest.raises(KeyError):
            fleet.submit("nope", dense=x)
    for name, got in (("a", got_a), ("b", got_b)):
        _, vdir, manifest = registry.resolve(reg, name)
        with ScorerService(models_dir=vdir,
                           ladder=tuple(manifest["ladder"]),
                           workspace_root=str(tmp_path)) as solo:
            want = np.asarray(solo.submit(dense=x)["mean"])
        np.testing.assert_array_equal(got, want)
    # the router really routes: two different models, two answers
    assert not np.array_equal(got_a, got_b)


def test_fleet_lru_evict_and_rewarm_roundtrip(tmp_path):
    from shifu_tpu.serve.fleet import FleetService

    reg = str(tmp_path / "reg")
    for i, name in enumerate(["a", "b", "c"]):
        _publish(reg, name, tmp_path, seed=i)
    budget = _budget_mb_fitting(reg, ["a", "b", "c"], fit=2)
    x = np.random.default_rng(4).normal(0, 1, (2, 12)) \
        .astype(np.float32)
    fleet = FleetService(reg, workspace_root=str(tmp_path),
                         hbm_budget_mb=budget)
    try:
        fleet.start()   # warms a, b, c in order; c's warm evicts a
        assert fleet.resident() == ["b", "c"]
        before = np.asarray(fleet.submit("a", dense=x)["mean"])
        # re-warming a evicted b (the least recently used resident)
        assert "a" in fleet.resident()
        assert "b" not in fleet.resident()
        fl = fleet.stats()["fleet"]
        assert fl["models_resident"] == 2
        assert fl["evictions"] == 2
        assert fl["rewarm_s"] > 0.0
        # b round-trips through its own evict + re-warm bitwise clean,
        # and a second hit on a (still resident) re-warms nothing
        b_scores = np.asarray(fleet.submit("b", dense=x)["mean"])
        again = np.asarray(fleet.submit("a", dense=x)["mean"])
        np.testing.assert_array_equal(before, again)
        assert np.asarray(b_scores).shape == (2,)
        assert fleet.stats()["fleet"]["evictions"] >= 3
    finally:
        fleet.close()


def test_promote_then_evict_hot_swaps_model_version(tmp_path):
    """A registry publish while the fleet runs takes effect at the
    model's next re-warm: HEAD is re-resolved, so promote-then-evict
    hot-swaps the version without a process restart."""
    from shifu_tpu.serve.fleet import FleetService

    reg = str(tmp_path / "reg")
    for i, name in enumerate(["a", "b"]):
        _publish(reg, name, tmp_path, seed=i)
    budget = _budget_mb_fitting(reg, ["a", "b"], fit=1)
    x = np.random.default_rng(7).normal(0, 1, (2, 12)) \
        .astype(np.float32)
    fleet = FleetService(reg, workspace_root=str(tmp_path),
                         hbm_budget_mb=budget)
    try:
        fleet.start()   # warms a then b; b's warm evicts a
        assert fleet.resident() == ["b"]
        old = np.asarray(fleet.submit("a", dense=x)["mean"])
        assert fleet.stats()["models"]["a"]["version"] == "v001"
        # promote a new version of a, then force its evict by
        # touching b (a becomes LRU) — next hit re-warms at HEAD
        assert _publish(reg, "a", tmp_path, seed=9) == "v002"
        fleet.submit("b", dense=x)
        assert fleet.resident() == ["b"]
        new = np.asarray(fleet.submit("a", dense=x)["mean"])
        assert fleet.stats()["models"]["a"]["version"] == "v002"
        assert not np.array_equal(old, new)
    finally:
        fleet.close()


def test_fleet_route_fault_names_site_and_recovers(tmp_path,
                                                   monkeypatch):
    from shifu_tpu.serve.fleet import FleetService

    reg = str(tmp_path / "reg")
    _publish(reg, "a", tmp_path, seed=0)
    x = np.zeros((2, 12), np.float32)
    with FleetService(reg, workspace_root=str(tmp_path),
                      hbm_budget_mb=0) as fleet:
        monkeypatch.setenv("SHIFU_TPU_FAULT", "serve.route:oserror:1")
        resilience.reset_faults()
        with pytest.raises(OSError,
                           match="injected oserror at serve.route"):
            fleet.submit("a", dense=x)
        monkeypatch.delenv("SHIFU_TPU_FAULT")
        resilience.reset_faults()
        out = fleet.submit("a", dense=x)
        assert np.asarray(out["mean"]).shape == (2,)


# ---------------------------------------------------------------------------
# admission: priority shed + hysteresis
# ---------------------------------------------------------------------------

def test_low_priority_sheds_while_high_keeps_flowing(tmp_path):
    from shifu_tpu.serve.fleet import FleetService, ShedReject

    reg = str(tmp_path / "reg")
    _publish(reg, "hi", tmp_path, seed=0, priority="high")
    _publish(reg, "lo", tmp_path, seed=1, priority="low")
    x = np.zeros((2, 12), np.float32)
    with FleetService(reg, workspace_root=str(tmp_path),
                      hbm_budget_mb=0, slo_p99_ms=50.0) as fleet:
        # breach: a window of 200ms high-priority latencies
        for _ in range(32):
            fleet._note_latency("high", 0.2)
        with pytest.raises(ShedReject) as ei:
            fleet.submit("lo", dense=x)
        assert isinstance(ei.value, queue.Full)   # uniform 429 path
        assert ei.value.retry_after_s > 0
        # high-priority traffic is never shed
        out = fleet.submit("hi", dense=x)
        assert np.asarray(out["mean"]).shape == (2,)
        st = fleet.stats()
        assert st["shedding"] is True
        assert st["fleet"]["shed_rate"] > 0
        assert st["rejected_by_class"]["low"] >= 1
        assert st["fleet"]["p99_ms_by_class"]["high"] > 50.0
        # recovery: fill the rolling window with sub-SLO latencies —
        # the hysteresis releases below 0.7x SLO and low flows again
        for _ in range(64):
            fleet._note_latency("high", 0.001)
        out = fleet.submit("lo", dense=x)
        assert np.asarray(out["mean"]).shape == (2,)
        assert fleet.stats()["shedding"] is False


# ---------------------------------------------------------------------------
# SLO autotuner
# ---------------------------------------------------------------------------

def test_autotuner_steers_and_converges(tmp_path, monkeypatch):
    from shifu_tpu.obs.health import store as health_store
    from shifu_tpu.serve.fleet import FleetService, SloAutotuner

    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    root = str(tmp_path)
    reg = os.path.join(root, "reg")
    _publish(reg, "a", tmp_path, seed=0, ladder=(1, 4, 16),
             max_delay_ms=4.0)
    st = health_store.store(root)

    def feed(p99_ms, n=25):
        for _ in range(n):
            st.emit("serve.p99_ms", p99_ms, model="a")
        st.flush()

    with FleetService(reg, workspace_root=root,
                      hbm_budget_mb=0) as fleet:
        entry = fleet._entries["a"]
        tuner = SloAutotuner(fleet, slo_p99_ms=50.0)

        feed(120.0)            # way over SLO → halve the deadline
        (rec,) = tuner.step()
        assert rec["p99_ms_before"] == 120.0
        assert rec["max_delay_ms_before"] == 4.0
        assert rec["max_delay_ms_after"] == 2.0
        # applied live, not just recorded
        assert entry.max_delay_s == pytest.approx(0.002)
        assert entry.service._batcher.max_delay == pytest.approx(0.002)

        feed(5.0)              # far under SLO → grow 1.25x
        (rec,) = tuner.step()
        assert rec["max_delay_ms_after"] == pytest.approx(2.5)

        feed(30.0)             # inside the band → converged, no-op
        (rec,) = tuner.step()
        assert rec["max_delay_ms_after"] == rec["max_delay_ms_before"]
        (rec2,) = tuner.step()
        assert rec2["max_delay_ms_after"] == rec["max_delay_ms_after"]

        # observed sizes never left the bottom rung → the proposal
        # trims the ladder (one rung of headroom) for the next re-warm
        fleet.submit("a", dense=np.zeros((1, 12), np.float32))
        (rec,) = tuner.step()
        assert rec["ladder"] == [1, 4]
        assert entry.ladder == (1, 4)


# ---------------------------------------------------------------------------
# HTTP front end: routing, 429 + Retry-After, labeled metrics
# ---------------------------------------------------------------------------

def test_http_fleet_routing_shed_and_metrics(tmp_path):
    from shifu_tpu.serve.fleet import FleetService
    from shifu_tpu.serve.http import HttpFrontEnd

    reg = str(tmp_path / "reg")
    _publish(reg, "a", tmp_path, seed=0, priority="high")
    _publish(reg, "lo", tmp_path, seed=1, priority="low")
    x = np.random.default_rng(6).normal(0, 1, (3, 12)) \
        .astype(np.float32)
    body = json.dumps({"dense": x.tolist()}).encode()
    hdrs = {"Content-Type": "application/json"}
    with FleetService(reg, workspace_root=str(tmp_path),
                      hbm_budget_mb=0, slo_p99_ms=50.0) as fleet:
        want = np.asarray(fleet.submit("a", dense=x)["mean"])
        front = HttpFrontEnd(fleet=fleet, host="127.0.0.1",
                             port=0).start()
        try:
            host, port = front.address
            base = f"http://{host}:{port}"

            req = urllib.request.Request(base + "/score/a", data=body,
                                         headers=hdrs)
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
            np.testing.assert_allclose(
                np.asarray(payload["scores"]["mean"], np.float64),
                want, rtol=1e-6, atol=1e-7)   # json float round-trip

            # unknown model and the un-routed /score both 404 in
            # fleet mode (routing is explicit)
            for path in ("/score/nope", "/score"):
                bad = urllib.request.Request(base + path, data=body,
                                             headers=hdrs)
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(bad, timeout=10)
                assert ei.value.code == 404

            # engage the shed switch → low-priority POST answers 429
            # with a Retry-After hint
            for _ in range(32):
                fleet._note_latency("high", 0.2)
            shed = urllib.request.Request(base + "/score/lo",
                                          data=body, headers=hdrs)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(shed, timeout=10)
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1

            with urllib.request.urlopen(base + "/stats",
                                        timeout=10) as resp:
                stats = json.loads(resp.read())
            from shifu_tpu import profiling
            assert set(stats["fleet"]) == set(profiling.FLEET_FIELDS)
            assert stats["models"]["a"]["priority"] == "high"

            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            assert "shifu_fleet_models_resident" in text
            assert 'shifu_serve_requests_total{model="a",' \
                   'priority="high"}' in text
            assert 'shifu_serve_rejected_total{priority="low"}' in text

            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as resp:
                health = json.loads(resp.read())
            assert health["ok"] is True
            assert set(health["models"]) == {"a", "lo"}
        finally:
            front.close()
