"""GBDT/RF tests: kernel-level tree building and the full tree
pipeline (reference analog: core/dtrain/DTTest + dt unit tests)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tpu.models import gbdt
from shifu_tpu.models.gbdt import TreeConfig


def _binned(rng, n=2000, c=4, n_bins=17):
    """Separable binned data: bin index of col 0 drives the label."""
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    y = (bins[:, 0] >= (n_bins - 1) // 2).astype(np.float32)
    noise = rng.random(n) < 0.1
    y = np.where(noise, 1 - y, y)
    return bins, y


def test_feature_subset_count():
    assert gbdt.feature_subset_count("ALL", 10) == 10
    assert gbdt.feature_subset_count("HALF", 10) == 5
    assert gbdt.feature_subset_count("SQRT", 100) == 10
    assert gbdt.feature_subset_count("LOG2", 64) == 6
    assert gbdt.feature_subset_count("TWOTHIRDS", 9) == 6
    assert gbdt.feature_subset_count("3", 10) == 3


def test_single_tree_finds_informative_split(rng):
    bins, y = _binned(rng)
    cfg = TreeConfig(max_depth=3, n_bins=17)
    grad = -(y)  # RF-style: leaf = mean(y)
    hess = np.ones_like(y)
    tree = gbdt.build_tree(cfg, jnp.asarray(bins.T), jnp.asarray(grad),
                           jnp.asarray(hess),
                           jnp.ones(bins.shape[1], jnp.float32))
    # root must split on feature 0 near the middle bin
    assert int(tree["feature"][0]) == 0
    assert abs(int(tree["bin"][0]) - (17 - 1) // 2) <= 1


def test_tree_predict_partitions(rng):
    bins, y = _binned(rng)
    cfg = TreeConfig(max_depth=4, n_bins=17)
    tree = gbdt.build_tree(cfg, jnp.asarray(bins.T), jnp.asarray(-(y)),
                           jnp.asarray(np.ones_like(y)),
                           jnp.ones(bins.shape[1], jnp.float32))
    pred = np.asarray(gbdt.predict_trees(
        jax.tree.map(lambda a: a[None], tree), jnp.asarray(bins.T), 4, 17))[0]
    # leaf means approximate P(y|leaf): high AUC
    from shifu_tpu.ops.metrics import auc
    a = float(auc(jnp.asarray(pred), jnp.asarray(y)))
    assert a > 0.85


def test_landing_nodes_match_tree_walk(rng):
    """build_tree(return_nodes=True)'s landing nodes gather the exact
    same per-row leaf values as the predict_trees re-walk — the
    boosting update's one-gather shortcut must be bit-identical."""
    bins, y = _binned(rng, n=3000, c=5)
    cfg = TreeConfig(max_depth=4, n_bins=17)
    binsT = jnp.asarray(bins.T)
    tree, nodes = gbdt.build_tree(
        cfg, binsT, jnp.asarray(-(y)), jnp.asarray(np.ones_like(y)),
        jnp.ones(bins.shape[1], jnp.float32), return_nodes=True)
    via_nodes = np.asarray(tree["leaf_value"][nodes])
    via_walk = np.asarray(gbdt.predict_trees(
        jax.tree.map(lambda a: a[None], tree), binsT, 4, 17))[0]
    np.testing.assert_array_equal(via_nodes, via_walk)
    # every landing node is a leaf
    assert bool(np.asarray(tree["is_leaf"])[np.asarray(nodes)].all())


def test_gbt_boosting_reduces_error(rng):
    bins, y = _binned(rng, n=3000)
    cfg = TreeConfig(max_depth=3, n_bins=17, learning_rate=0.3, loss="log")
    trees, val_errs = gbdt.build_gbt(
        cfg, bins[:2400], y[:2400], np.ones(2400, np.float32), 20,
        val_data=(jnp.asarray(bins[2400:]), jnp.asarray(y[2400:])))
    assert len(val_errs) == 20
    assert val_errs[-1] < val_errs[0] * 0.8
    assert trees["feature"].shape[0] == 20


def test_gbt_missing_direction(rng):
    """Rows with the missing bin get routed by the learned default
    direction, not dropped."""
    n, n_bins = 2000, 9
    bins = rng.integers(0, n_bins - 1, size=(n, 2)).astype(np.int32)
    y = (bins[:, 0] >= 4).astype(np.float32)
    miss = rng.random(n) < 0.3
    bins[miss, 0] = n_bins - 1  # missing bin
    y[miss] = 1.0               # missing is predictive of positive
    cfg = TreeConfig(max_depth=2, n_bins=n_bins, learning_rate=0.5, loss="log")
    trees, _ = gbdt.build_gbt(cfg, bins, y, np.ones(n, np.float32), 10)
    meta = {"kind": "gbt", "treeConfig": {"max_depth": 2, "n_bins": n_bins,
                                          "learning_rate": 0.5, "loss": "log"}}
    # score missing rows directly on bin matrix
    pred = np.asarray(gbdt.predict_trees(
        jax.tree.map(jnp.asarray, trees), jnp.asarray(bins.T), 2, n_bins))
    raw = 0.5 * pred.sum(axis=0)
    p = 1 / (1 + np.exp(-raw))
    assert p[miss].mean() > 0.8  # learned that missing → positive


def test_route_level_onehot_matches_gather(rng, monkeypatch):
    """SHIFU_TPU_GBT_ROUTE=onehot (one-hot multiply-reduce feature
    lookup) must route every row exactly like the gather formulation
    — same child ids for any tree state."""
    import jax.numpy as jnp
    from shifu_tpu.models.gbdt import TreeConfig, _route_level
    cfg = TreeConfig(max_depth=4, n_bins=64, learning_rate=0.1,
                     loss="log")
    c, r = 7, 5000
    binsT = jnp.asarray(rng.integers(0, 64, (c, r)).astype(np.int32))
    tree = {"feature": jnp.asarray(
                rng.integers(-1, c, 31).astype(np.int32)),
            "bin": jnp.asarray(rng.integers(0, 63, 31).astype(np.int32)),
            "default_left": jnp.asarray(rng.random(31) < 0.5)}
    node = jnp.asarray(rng.integers(3, 7, r).astype(np.int32))
    monkeypatch.setenv("SHIFU_TPU_GBT_ROUTE", "gather")
    a = np.asarray(_route_level(cfg, tree, binsT, node, 2))
    monkeypatch.setenv("SHIFU_TPU_GBT_ROUTE", "onehot")
    b = np.asarray(_route_level(cfg, tree, binsT, node, 2))
    np.testing.assert_array_equal(a, b)


def test_rf_vmapped_forest(rng):
    bins, y = _binned(rng)
    cfg = TreeConfig(max_depth=4, n_bins=17)
    trees = gbdt.build_rf(cfg, bins, y, np.ones_like(y), n_trees=8,
                          subset_strategy="SQRT", bagging_rate=1.0, seed=7)
    assert trees["feature"].shape == (8, cfg.n_nodes)
    pred = np.asarray(gbdt.predict_trees(
        jax.tree.map(jnp.asarray, trees), jnp.asarray(bins.T), 4, 17)).mean(axis=0)
    from shifu_tpu.ops.metrics import auc
    assert float(auc(jnp.asarray(pred), jnp.asarray(y))) > 0.85
    assert pred.min() >= -1e-5 and pred.max() <= 1 + 1e-5  # mean-label leaves


def test_min_instances_respected(rng):
    bins, y = _binned(rng, n=50)
    cfg = TreeConfig(max_depth=6, n_bins=17, min_instances_per_node=20)
    tree = gbdt.build_tree(cfg, jnp.asarray(bins.T), jnp.asarray(-(y)),
                           jnp.asarray(np.ones_like(y)),
                           jnp.ones(bins.shape[1], jnp.float32))
    # with 50 rows and min 20 per side, depth ≥ 2 splits are impossible
    deep_internal = np.asarray(tree["feature"][3:15])
    assert (deep_internal < 0).all() or (np.asarray(tree["is_leaf"][3:15])[
        deep_internal >= 0] == False).sum() == 0  # noqa: E712


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("alg,params", [
    ("GBT", {"TreeNum": 25, "MaxDepth": 4, "LearningRate": 0.3,
             "Loss": "log"}),
    ("RF", {"TreeNum": 12, "MaxDepth": 5,
            "FeatureSubsetStrategy": "TWOTHIRDS"}),
])
def test_full_pipeline_tree(tmp_path, rng, alg, params):
    from tests.synth import make_model_set
    from tests.test_train import run_pipeline
    root = make_model_set(tmp_path, rng, n_rows=2500, algorithm=alg,
                          train_params=params)
    ctx = run_pipeline(root)
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85, f"{alg} AUC {perf['areaUnderRoc']}"
    ext = alg.lower()
    assert os.path.exists(ctx.path_finder.model_path(0, ext))


def test_gbt_continuous_appends_trees(tmp_path, rng):
    from tests.synth import make_model_set
    from shifu_tpu.processor.base import ProcessorContext
    from shifu_tpu.processor import (init as init_proc, stats as stats_proc,
                                     norm as norm_proc, train as train_proc)
    from shifu_tpu.models.spec import load_model
    root = make_model_set(tmp_path, rng, n_rows=1200, algorithm="GBT",
                          train_params={"TreeNum": 5, "MaxDepth": 3,
                                        "LearningRate": 0.3, "Loss": "log"})
    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        proc.run(ctx)
    _, _, params = load_model(ctx.path_finder.model_path(0, "gbt"))
    assert params["trees"]["feature"].shape[0] == 5
    # continuous: 5 more trees appended
    ctx = ProcessorContext.load(root)
    ctx.model_config.train.isContinuous = True
    train_proc.run(ctx)
    _, _, params = load_model(ctx.path_finder.model_path(0, "gbt"))
    assert params["trees"]["feature"].shape[0] == 10

    # resuming a checkpoint saved BEFORE gain tracking (no 'gain' key)
    # must backfill zeros instead of crashing on pytree mismatch
    from shifu_tpu.models.spec import save_model
    kind, meta, params = load_model(ctx.path_finder.model_path(0, "gbt"))
    legacy_trees = {k: v for k, v in params["trees"].items() if k != "gain"}
    save_model(ctx.path_finder.model_path(0, "gbt"), kind, meta,
               {"trees": legacy_trees, "tables": params["tables"]})
    ctx = ProcessorContext.load(root)
    ctx.model_config.train.isContinuous = True
    train_proc.run(ctx)
    _, _, params = load_model(ctx.path_finder.model_path(0, "gbt"))
    assert params["trees"]["feature"].shape[0] == 15
    assert "gain" in params["trees"]


def test_pallas_histogram_matches_scatter(rng):
    """The Pallas MXU histogram kernel (ops/pallas_hist.py) matches the
    XLA scatter-add formulation bit-for-bit-ish (float32 sums)."""
    import os

    import jax.numpy as jnp

    from shifu_tpu.models.gbdt import _level_histograms
    from shifu_tpu.ops.pallas_hist import level_histograms_pallas

    R, C, B, S = 700, 5, 8, 4
    bins = jnp.asarray(rng.integers(0, B, (R, C)).astype(np.int32))
    node = jnp.asarray(rng.integers(-1, 2 * S, R).astype(np.int32))
    grad = jnp.asarray(rng.normal(0, 1, R).astype(np.float32))
    hess = jnp.asarray(rng.uniform(0.5, 1.5, R).astype(np.float32))

    old = os.environ.get("SHIFU_TPU_HIST")
    try:
        os.environ["SHIFU_TPU_HIST"] = "xla"
        g0, h0 = _level_histograms(bins.T, node, grad, hess, 0, S, B)
        slot = jnp.where((node >= 0) & (node < S), node, S)
        g1, h1 = level_histograms_pallas(bins.T, slot, grad, hess, S, B,
                                         row_tile=128, col_tile=5,
                                         interpret=True)
    finally:
        if old is None:
            os.environ.pop("SHIFU_TPU_HIST", None)
        else:
            os.environ["SHIFU_TPU_HIST"] = old
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(h0), np.asarray(h1),
                               rtol=1e-5, atol=1e-3)


def test_gbt_trains_through_pallas_kernel(tmp_path, rng):
    """Full GBT training with SHIFU_TPU_HIST=pallas (interpret mode on
    CPU) reaches the same quality as the scatter path."""
    import os

    from tests.synth import make_model_set
    from shifu_tpu.processor import (eval as eval_proc, init as init_proc,
                                     norm as norm_proc, stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=1000, algorithm="GBT",
                          train_params={"TreeNum": 8, "MaxDepth": 3,
                                        "LearningRate": 0.3})
    old = os.environ.get("SHIFU_TPU_HIST")
    os.environ["SHIFU_TPU_HIST"] = "pallas"
    try:
        for proc in (init_proc, stats_proc, norm_proc, train_proc):
            ctx = ProcessorContext.load(root)
            assert proc.run(ctx) == 0
        ctx = ProcessorContext.load(root)
        assert eval_proc.run(ctx) == 0
    finally:
        if old is None:
            os.environ.pop("SHIFU_TPU_HIST", None)
        else:
            os.environ["SHIFU_TPU_HIST"] = old
    import json
    perf = json.load(open(ctx.path_finder.eval_performance_path("Eval1")))
    assert perf["areaUnderRoc"] > 0.85


def test_streaming_gbt_matches_resident(rng):
    """Chunked histogram accumulation (build_gbt_streaming) grows the
    same ensemble as the resident builder: histograms are additive over
    row chunks, so splits must agree (dt/DTWorker.java:914-944
    Combinable merge semantics, here chunk partial sums)."""
    from shifu_tpu.models import gbdt

    r, c, n_bins = 700, 6, 10
    bins = rng.integers(0, n_bins - 1, (r, c)).astype(np.int32)
    beta = rng.normal(0, 1, c)
    y = ((bins @ beta) > np.median(bins @ beta)).astype(np.float32)
    w = np.ones(r, np.float32)
    cfg = gbdt.TreeConfig(max_depth=3, n_bins=n_bins, learning_rate=0.3,
                          loss="log")
    resident, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=5)
    streaming, _ = gbdt.build_gbt_streaming(cfg, bins, y, w, n_trees=5,
                                            chunk_rows=150)
    np.testing.assert_array_equal(resident["feature"],
                                  streaming["feature"])
    np.testing.assert_array_equal(resident["is_leaf"],
                                  streaming["is_leaf"])
    np.testing.assert_allclose(resident["leaf_value"],
                               streaming["leaf_value"], rtol=1e-4,
                               atol=1e-5)


def test_streaming_tree_pipeline(tmp_path, rng):
    """trainOnDisk routes GBT through the out-of-core path: bins
    materialize to a uint8 on-disk matrix and the model evaluates."""
    import json

    from tests.synth import make_model_set
    from shifu_tpu.processor import (eval as eval_proc, init as init_proc,
                                     norm as norm_proc, stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=1200, algorithm="GBT",
                          train_params={"TreeNum": 8, "MaxDepth": 3,
                                        "LearningRate": 0.3,
                                        "ChunkRows": 300})
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["train"]["trainOnDisk"] = True
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))
    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    ctx = ProcessorContext.load(root)
    assert eval_proc.run(ctx) == 0
    bins_path = os.path.join(ctx.path_finder.cleaned_data_path(),
                             "bins.npy")
    assert os.path.exists(bins_path)
    assert np.load(bins_path, mmap_mode="r").dtype == np.uint8
    perf = json.load(open(ctx.path_finder.eval_performance_path("Eval1")))
    assert perf["areaUnderRoc"] > 0.85


def test_streaming_rf_smoke(rng):
    """Out-of-core RF: sequential per-tree builds with Philox Poisson
    weights produce a working ensemble."""
    from shifu_tpu.models import gbdt

    r, c, n_bins = 600, 5, 8
    bins = rng.integers(0, n_bins - 1, (r, c)).astype(np.int32)
    beta = rng.normal(0, 1, c)
    y = ((bins @ beta) > np.median(bins @ beta)).astype(np.float32)
    w = np.ones(r, np.float32)
    cfg = gbdt.TreeConfig(max_depth=3, n_bins=n_bins)
    trees = gbdt.build_rf_streaming(cfg, bins, y, w, n_trees=4,
                                    subset_strategy="ALL",
                                    bagging_rate=1.0, seed=3,
                                    chunk_rows=200)
    assert trees["feature"].shape[0] == 4
    import jax.numpy as jnp
    scores = np.mean(np.asarray(gbdt.predict_trees(
        jax.tree.map(jnp.asarray, trees), jnp.asarray(bins.T),
        cfg.max_depth, cfg.n_bins)), axis=0)
    from shifu_tpu.ops.metrics import auc
    assert float(auc(jnp.asarray(scores), jnp.asarray(y))) > 0.8


def test_pallas_tile_derivation_across_bin_widths(rng):
    """derive_tiles sizes (row, col) tiles to the VMEM budget so the
    kernel holds for n_bins ∈ {16, 64, 256} (VERDICT r2 Weak #8);
    correctness re-checked in interpret mode at each width."""
    import jax.numpy as jnp

    from shifu_tpu.models.gbdt import _level_histograms
    from shifu_tpu.ops.pallas_hist import (derive_tiles,
                                           level_histograms_pallas)

    budget = 64 << 20
    for n_bins in (16, 64, 256):
        rt, ct = derive_tiles(128, 64, n_bins)
        usage = 4 * (n_bins * ct * rt + ct * rt + 8 * rt + 4 * 64 * rt
                     + 4 * 64 * ct * n_bins)
        assert usage <= budget, (n_bins, rt, ct, usage)
        assert rt >= 64 and ct >= 8

    R, C, S = 600, 4, 4
    for n_bins in (16, 64, 256):
        bins = jnp.asarray(rng.integers(0, n_bins, (R, C)).astype(np.int32))
        node = jnp.asarray(rng.integers(0, S, R).astype(np.int32))
        grad = jnp.asarray(rng.normal(0, 1, R).astype(np.float32))
        hess = jnp.ones(R, np.float32)
        g0, h0 = _level_histograms(bins.T, node, grad, hess, 0, S, n_bins)
        # derived tiles (row_tile=0/col_tile=0 → derive), interpret mode
        g1, h1 = level_histograms_pallas(bins.T, node, grad, hess, S,
                                         n_bins, interpret=True)
        np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                                   rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(np.asarray(h0), np.asarray(h1),
                                   rtol=1e-5, atol=1e-3)


def test_bf16_truncation_bound_on_histograms(rng):
    """The DEFAULT-precision MXU path truncates grad/hess inputs to
    bf16 (the one-hot side is exact). Emulate exactly that truncation
    and bound the histogram error — the CI-side evidence for the
    '~0.3% relative' claim in ops/pallas_hist.py (ADVICE r2 low #1);
    the hardware path itself is covered by `bench.py --task hist_pallas`
    vs `hist_xla` checksums on the real chip."""
    import jax.numpy as jnp

    from shifu_tpu.models.gbdt import _level_histograms

    R, C, B, S = 4000, 6, 16, 8
    bins = jnp.asarray(rng.integers(0, B, (R, C)).astype(np.int32))
    node = jnp.asarray(rng.integers(0, S, R).astype(np.int32))
    grad = jnp.asarray(rng.normal(0, 1, R).astype(np.float32))
    hess = jnp.asarray(rng.uniform(0.5, 1.5, R).astype(np.float32))

    g0, h0 = _level_histograms(bins.T, node, grad, hess, 0, S, B)
    gt = grad.astype(jnp.bfloat16).astype(jnp.float32)
    ht = hess.astype(jnp.bfloat16).astype(jnp.float32)
    g1, h1 = _level_histograms(bins.T, node, gt, ht, 0, S, B)

    # hessians are positive sums: relative error bounded by bf16 eps
    h_rel = float(jnp.max(jnp.abs(h1 - h0) / jnp.maximum(h0, 1e-6)))
    assert h_rel < 0.01, h_rel
    # gradient sums can cancel; bound against the bucket L1 mass
    gmass0, _ = _level_histograms(bins.T, node, jnp.abs(grad), hess,
                                  0, S, B)
    g_rel = float(jnp.max(jnp.abs(g1 - g0) /
                          jnp.maximum(np.asarray(gmass0), 1e-6)))
    assert g_rel < 0.01, g_rel


def test_streaming_bins_cache_reused(tmp_path, rng):
    """Repeated streaming trains skip the rebinning pass: bins.npy is
    keyed by a hash of the binning tables + layout identity, reused
    when unchanged and rebuilt when the tables change (VERDICT r2
    Weak #6 / Next #9)."""
    import json

    from tests.synth import make_model_set
    from shifu_tpu.processor import (init as init_proc, norm as norm_proc,
                                     stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=900, algorithm="GBT",
                          train_params={"TreeNum": 4, "MaxDepth": 3,
                                        "LearningRate": 0.3,
                                        "ChunkRows": 300})
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["train"]["trainOnDisk"] = True
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))
    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    bins_path = os.path.join(ctx.path_finder.cleaned_data_path(),
                             "bins.npy")
    meta_path = os.path.join(ctx.path_finder.cleaned_data_path(),
                             "bins.meta.json")
    assert os.path.exists(meta_path)
    mtime1 = os.stat(bins_path).st_mtime_ns

    # second train: same tables → bin matrix reused, not rewritten
    ctx = ProcessorContext.load(root)
    assert train_proc.run(ctx) == 0
    assert os.stat(bins_path).st_mtime_ns == mtime1

    # stats tables change (different maxNumBin) → stale file replaced
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["stats"]["maxNumBin"] = 6
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))
    for proc in (stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    assert os.stat(bins_path).st_mtime_ns != mtime1
    key2 = json.load(open(meta_path))["key"]
    assert key2


def test_hist_subtraction_matches_direct(rng, monkeypatch):
    """Sibling-subtraction histograms (left via kernel, right =
    parent − left) grow the same trees as direct per-level histograms
    — the 2× histogram-work GBDT optimization must not change
    results."""
    import jax.numpy as jnp

    from shifu_tpu.models import gbdt

    R, C, B = 3000, 6, 16
    bins = rng.integers(0, B - 1, (R, C)).astype(np.int32)
    binsT = jnp.asarray(bins.T)
    beta = rng.normal(0, 1, C)
    y = ((bins @ beta) / np.sqrt(C) + rng.normal(0, 2, R) >
         np.median(bins @ beta) / np.sqrt(C)).astype(np.float32)
    w = np.ones(R, np.float32)
    cfg = gbdt.TreeConfig(max_depth=4, n_bins=B, learning_rate=0.3,
                          loss="log")

    # subtract is a STATIC jit arg on the tree builders (an env flip
    # after first compile would silently hit the cached trace)
    fm = jnp.ones(C, jnp.float32)
    t_direct = gbdt.build_tree(cfg, binsT, jnp.asarray(y * w),
                               jnp.asarray(w), fm, subtract=False)
    t_sub = gbdt.build_tree(cfg, binsT, jnp.asarray(y * w),
                            jnp.asarray(w), fm, subtract=True)
    t_direct = {k: np.asarray(v) for k, v in t_direct.items()}
    t_sub = {k: np.asarray(v) for k, v in t_sub.items()}

    np.testing.assert_array_equal(t_direct["feature"], t_sub["feature"])
    np.testing.assert_array_equal(t_direct["bin"], t_sub["bin"])
    np.testing.assert_array_equal(t_direct["is_leaf"], t_sub["is_leaf"])
    np.testing.assert_allclose(t_direct["leaf_value"],
                               t_sub["leaf_value"], rtol=1e-4, atol=1e-5)

    # RF lockstep build too
    gT = jnp.asarray(np.stack([y * w, y * w * 0.5]))
    hT = jnp.asarray(np.stack([w, w * 0.5]))
    fm2 = jnp.ones((2, C), jnp.float32)
    f_direct = gbdt.build_forest(gbdt.TreeConfig(max_depth=3, n_bins=B),
                                 binsT, gT, hT, fm2, subtract=False)
    f_sub = gbdt.build_forest(gbdt.TreeConfig(max_depth=3, n_bins=B),
                              binsT, gT, hT, fm2, subtract=True)
    np.testing.assert_array_equal(np.asarray(f_direct["feature"]),
                                  np.asarray(f_sub["feature"]))
    np.testing.assert_allclose(np.asarray(f_direct["leaf_value"]),
                               np.asarray(f_sub["leaf_value"]),
                               rtol=1e-4, atol=1e-5)


def test_gbt_scan_matches_per_round_loop(rng):
    """The one-dispatch lax.scan boosting path (no val_data) must build
    bit-identical trees to the per-round host loop (val_data present,
    early stop off) — same rounds, one dispatch vs n."""
    from shifu_tpu.models import gbdt
    r, c = 3000, 6
    bins = rng.integers(0, 7, (r, c)).astype(np.int32)
    y = (bins[:, 0] + bins[:, 1] > 6).astype(np.float32)
    w = np.ones(r, np.float32)
    cfg = gbdt.TreeConfig(max_depth=3, n_bins=8, learning_rate=0.3,
                          loss="log")
    scan_trees, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=4)
    loop_trees, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=4,
                                   val_data=(bins, y))
    for k in scan_trees:
        np.testing.assert_array_equal(scan_trees[k], loop_trees[k], err_msg=k)


def test_gbt_grouped_dispatch_matches_single(rng, monkeypatch):
    """SHIFU_TPU_GBT_SCAN_GROUP splits the device-side boosting scan
    into bounded-size dispatches (tunnel-liveness guard); grouping must
    not change the math — trees bit-identical to the one-dispatch
    build, including an uneven trailing group."""
    from shifu_tpu.models import gbdt
    r, c = 3000, 6
    bins = rng.integers(0, 7, (r, c)).astype(np.int32)
    y = (bins[:, 0] + bins[:, 1] > 6).astype(np.float32)
    w = np.ones(r, np.float32)
    cfg = gbdt.TreeConfig(max_depth=3, n_bins=8, learning_rate=0.3,
                          loss="log")
    monkeypatch.delenv("SHIFU_TPU_GBT_SCAN_GROUP", raising=False)
    one, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=5)
    monkeypatch.setenv("SHIFU_TPU_GBT_SCAN_GROUP", "2")  # 2+2+1
    grouped, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=5)
    for k in one:
        np.testing.assert_array_equal(one[k], grouped[k], err_msg=k)
