"""GBT on-device state tiers and lockstep bagging.

Pins the PR-12 contracts: (1) the resident row-state tier of
`build_gbt_streaming` grows the SAME ensemble as the host-numpy tier —
and does it with ZERO device→host syncs inside a level and at most one
per boosting round, asserted via the pipeline `host_syncs` counter,
not eyeballed; (2) lockstep bagged boosting (`build_gbt_bagged`)
matches per-bag sequential `build_gbt` including per-bag early stop;
(3) the early-stop val metric is the shared `_val_error` on every
builder, so decisions can't diverge on metric arithmetic.

Parity notes: tree STRUCTURE (feature/bin/is_leaf/default_left) is
exact. Leaf values/gains are allclose at f32-ulp tolerances — the
resident tier computes the log-loss sigmoid with jax.nn.sigmoid where
the host tier uses numpy exp, and the lockstep build stacks per-bag
scatters that XLA may reassociate differently from the single-tree
build. Squared-loss gradients are the same f32 expression on both
tiers, so streaming parity there is bitwise.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tpu.data.pipeline import drain_stage_timers
from shifu_tpu.models import gbdt
from shifu_tpu.models.gbdt import TreeConfig


def _case(rng, n=900, c=7, n_bins=16, miss=0.05):
    bins = rng.integers(0, n_bins - 1, size=(n, c)).astype(np.int32)
    bins[rng.random((n, c)) < miss] = n_bins - 1
    y = (bins[:, 0] >= (n_bins - 1) // 2).astype(np.float32)
    flip = rng.random(n) < 0.1
    return bins, np.where(flip, 1 - y, y).astype(np.float32)


def _cfg(loss="squared", depth=3):
    return TreeConfig(max_depth=depth, n_bins=16,
                      min_instances_per_node=2, min_info_gain=0.0,
                      reg_lambda=1.0, learning_rate=0.1, loss=loss)


def _assert_tree_parity(a, b, leaf_rtol=1e-5, leaf_atol=1e-6):
    for k in ("feature", "bin", "is_leaf", "default_left"):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)
    for k in ("leaf_value", "gain"):
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   rtol=leaf_rtol, atol=leaf_atol,
                                   err_msg=k)


@pytest.mark.parametrize("loss", ["squared", "log"])
def test_resident_streaming_matches_host_tier(rng, monkeypatch, loss):
    bins, y = _case(rng)
    w = np.ones_like(y)
    cfg = _cfg(loss)
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "0")
    host_t, host_e = gbdt.build_gbt_streaming(
        cfg, bins, y, w, 4, valid_rate=0.2, chunk_rows=256,
        early_stop_window=3)
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "1")
    res_t, res_e = gbdt.build_gbt_streaming(
        cfg, bins, y, w, 4, valid_rate=0.2, chunk_rows=256,
        early_stop_window=3)
    # log loss: sigmoid ulp noise in the gradients amplifies through
    # the gain's sum-of-squares — wider (still f32-ulp-scale) band
    tol = dict(leaf_rtol=1e-4, leaf_atol=5e-5) if loss == "log" else {}
    _assert_tree_parity(host_t, res_t, **tol)
    assert len(host_e) == len(res_e)
    np.testing.assert_allclose(host_e, res_e, rtol=1e-6, atol=1e-7)


def test_resident_sync_budget(rng, monkeypatch):
    """THE acceptance gate: a resident-tier level performs zero
    device→host syncs and a round at most one — counted by the
    pipeline host_syncs counter that host_fetch bumps. A no-val build
    must show ZERO syncs total; with validation, exactly one per
    round (the early-stop decision fetch)."""
    bins, y = _case(rng, n=700)
    w = np.ones_like(y)
    cfg = _cfg()
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "1")

    drain_stage_timers()
    gbdt.build_gbt_streaming(cfg, bins, y, w, 3, chunk_rows=256)
    t = drain_stage_timers()
    assert t.get("host_syncs", 0) == 0, t

    n_rounds = 4
    gbdt.build_gbt_streaming(cfg, bins, y, w, n_rounds, valid_rate=0.2,
                             chunk_rows=256)
    t = drain_stage_timers()
    assert t.get("host_syncs", 0) == n_rounds, t

    # the host tier, same workload, syncs per chunk per level — the
    # counter is what makes the resident win falsifiable
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "0")
    gbdt.build_gbt_streaming(cfg, bins, y, w, n_rounds, valid_rate=0.2,
                             chunk_rows=256)
    t = drain_stage_timers()
    assert t.get("host_syncs", 0) > n_rounds * (cfg.max_depth + 1), t


def test_resident_state_mode_gating(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "1")
    assert gbdt.gbt_resident_state_mode(10 ** 12)
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "0")
    assert not gbdt.gbt_resident_state_mode(10)
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "auto")
    monkeypatch.setenv("SHIFU_TPU_GBT_STATE_BUDGET_MB", "1")
    # 24 B/train row + 12 B/val row vs a 1 MiB budget
    assert gbdt.gbt_resident_state_mode(40_000)
    assert not gbdt.gbt_resident_state_mode(40_000, 20_000)
    assert not gbdt.gbt_resident_state_mode(50_000)


def test_resident_resume_matches_host_tier(rng, monkeypatch):
    """init_trees (continuous training) warms predictions device-side
    on the resident tier — the appended trees must match the host
    tier's."""
    bins, y = _case(rng, n=600)
    w = np.ones_like(y)
    cfg = _cfg()
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "0")
    first, _ = gbdt.build_gbt_streaming(cfg, bins, y, w, 2,
                                        chunk_rows=256)
    host_t, _ = gbdt.build_gbt_streaming(cfg, bins, y, w, 2,
                                         chunk_rows=256,
                                         init_trees=first)
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "1")
    res_t, _ = gbdt.build_gbt_streaming(cfg, bins, y, w, 2,
                                        chunk_rows=256,
                                        init_trees=first)
    _assert_tree_parity(host_t, res_t)


def test_lockstep_bagged_matches_sequential(rng):
    """Each bag of the lockstep build must equal a sequential
    build_gbt run with the same bag weights — including per-bag early
    stop (different bags may stop at different rounds; each keeps
    exactly what its sequential loop would have kept)."""
    bins, y = _case(rng)
    vb, vy = _case(rng, n=300)
    cfg = _cfg()
    w_T = rng.poisson(1.0, size=(3, len(y))).astype(np.float32)
    w_T[w_T.sum(axis=1) == 0] = 1.0
    bag_out = gbdt.build_gbt_bagged(cfg, bins, y, w_T, 5,
                                    val_data=(vb, vy),
                                    early_stop_window=2)
    for b in range(3):
        seq_t, seq_e = gbdt.build_gbt(cfg, bins, y, w_T[b], 5,
                                      val_data=(vb, vy),
                                      early_stop_window=2)
        lk_t, lk_e = bag_out[b]
        assert seq_t["feature"].shape == lk_t["feature"].shape
        _assert_tree_parity(seq_t, lk_t)
        assert len(seq_e) == len(lk_e)
        np.testing.assert_allclose(seq_e, lk_e, rtol=1e-6, atol=1e-7)


def test_lockstep_bagged_noval_scan_matches_sequential(rng, monkeypatch):
    """The no-val lockstep path scans rounds device-side (grouped by
    SHIFU_TPU_GBT_SCAN_GROUP like build_gbt) — same ensembles."""
    monkeypatch.setenv("SHIFU_TPU_GBT_SCAN_GROUP", "2")
    bins, y = _case(rng, n=600)
    cfg = _cfg()
    w_T = rng.poisson(1.0, size=(2, len(y))).astype(np.float32)
    w_T[w_T.sum(axis=1) == 0] = 1.0
    bag_out = gbdt.build_gbt_bagged(cfg, bins, y, w_T, 3)
    for b in range(2):
        seq_t, _ = gbdt.build_gbt(cfg, bins, y, w_T[b], 3)
        _assert_tree_parity(seq_t, bag_out[b][0])


def test_forest_return_nodes_land_on_leaves(rng):
    """build_forest(return_nodes=True): per-tree landing nodes gather
    the same leaf values as the predict_trees re-walk — the lockstep
    boosting update's one-gather shortcut."""
    bins, y = _case(rng, n=800, c=5)
    cfg = _cfg(depth=4)
    binsT = jnp.asarray(bins.T)
    grad_T = jnp.asarray(np.stack([-y, -y * 0.5]).astype(np.float32))
    hess_T = jnp.ones_like(grad_T)
    masks = jnp.ones((2, 5), jnp.float32)
    trees, node_T = gbdt.build_forest(cfg, binsT, grad_T, hess_T, masks,
                                      return_nodes=True)
    via_nodes = np.asarray(jax.vmap(
        lambda tr, n: tr["leaf_value"][n])(trees, node_T))
    via_walk = np.asarray(gbdt.predict_trees(trees, binsT,
                                             cfg.max_depth, cfg.n_bins))
    np.testing.assert_array_equal(via_nodes, via_walk)


def test_val_metric_aligned_across_builders(rng, monkeypatch):
    """Satellite gate: build_gbt and both streaming tiers report the
    same per-round val errors (one shared _val_error definition) —
    early-stop decisions cannot diverge between builders."""
    bins, y = _case(rng, n=800)
    w = np.ones_like(y)
    cfg = _cfg(loss="log")
    n_val = 160
    n_train = len(y) - n_val
    # build_gbt takes an explicit (val_bins, val_y) split; streaming
    # takes the trailing fraction of the same layout
    _, res_e = gbdt.build_gbt(
        cfg, bins[:n_train], y[:n_train], w[:n_train], 3,
        val_data=(bins[n_train:], y[n_train:]))
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "0")
    _, host_e = gbdt.build_gbt_streaming(cfg, bins, y, w, 3,
                                         chunk_rows=256, n_val=n_val)
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "1")
    _, dev_e = gbdt.build_gbt_streaming(cfg, bins, y, w, 3,
                                        chunk_rows=256, n_val=n_val)
    np.testing.assert_allclose(res_e, host_e, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(host_e, dev_e, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# single-dispatch builds (SHIFU_TPU_TREE_SCAN): the fori_loop-over-
# levels builder must be BITWISE identical to the per-level host loop,
# and the resident streaming tier must build each tree in ONE dispatch
# ---------------------------------------------------------------------------

def _tree_bitwise(a, b, ctx=""):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"{ctx}:{k}")


@pytest.mark.parametrize("depth", [1, 2, 3, 5])
@pytest.mark.parametrize("subtract", [False, True])
def test_scan_tree_bitwise_matches_per_level(rng, monkeypatch, depth,
                                             subtract):
    """build_tree with the level scan on vs off: identical histograms
    scatter in identical row order, the masked folds write identical
    values, so the whole tree (and the landing nodes) is bit-equal —
    not allclose, equal."""
    bins, y = _case(rng, n=700, c=6)
    binsT = jnp.asarray(np.ascontiguousarray(bins.T))
    grad = jnp.asarray(-(y - 0.5))
    hess = jnp.ones_like(grad)
    fm = jnp.ones(6, jnp.float32)
    cfg = _cfg(depth=depth)

    def build(scan):
        monkeypatch.setenv("SHIFU_TPU_TREE_SCAN", scan)
        jax.clear_caches()  # scan mode resolves at trace time
        return gbdt.build_tree(cfg, binsT, grad, hess, fm,
                               subtract=subtract, return_nodes=True)

    t_loop, n_loop = build("0")
    t_scan, n_scan = build("1")
    _tree_bitwise(t_loop, t_scan, f"d{depth}/sub{subtract}")
    np.testing.assert_array_equal(np.asarray(n_loop), np.asarray(n_scan))


@pytest.mark.parametrize("subtract", [False, True])
def test_scan_forest_bitwise_matches_per_level(rng, monkeypatch,
                                               subtract):
    """build_forest (the lockstep multi-tree builder) under the same
    scan flip — per-tree feature masks and sibling subtraction
    included."""
    bins, y = _case(rng, n=600, c=5)
    binsT = jnp.asarray(np.ascontiguousarray(bins.T))
    grad_T = jnp.asarray(np.stack([-y, -y * 0.5, y - 0.3])
                         .astype(np.float32))
    hess_T = jnp.ones_like(grad_T)
    masks = jnp.asarray((rng.random((3, 5)) > 0.3).astype(np.float32))
    cfg = _cfg(depth=3)

    def build(scan):
        monkeypatch.setenv("SHIFU_TPU_TREE_SCAN", scan)
        jax.clear_caches()
        return gbdt.build_forest(cfg, binsT, grad_T, hess_T, masks,
                                 subtract=subtract, return_nodes=True)

    (t_loop, n_loop), (t_scan, n_scan) = build("0"), build("1")
    _tree_bitwise(t_loop, t_scan, f"forest/sub{subtract}")
    np.testing.assert_array_equal(np.asarray(n_loop), np.asarray(n_scan))


def test_resident_single_chunk_one_dispatch_per_tree(rng, monkeypatch):
    """THE dispatch gate: a single-chunk resident build with the scan
    on launches ONE device computation per tree (counted by the
    pipeline tree_build_dispatches counter); with the scan off it pays
    one per level plus the final-leaf pass. Trees bitwise identical
    either way, and the resident zero-host-sync contract holds on
    both paths."""
    bins, y = _case(rng, n=800)
    w = np.ones_like(y)
    cfg = _cfg(loss="log")
    n_trees = 3
    monkeypatch.setenv("SHIFU_TPU_GBT_RESIDENT_STATE", "1")

    def run(scan):
        monkeypatch.setenv("SHIFU_TPU_TREE_SCAN", scan)
        jax.clear_caches()
        drain_stage_timers()
        trees, _ = gbdt.build_gbt_streaming(cfg, bins, y, w, n_trees,
                                            chunk_rows=1 << 20)
        return trees, drain_stage_timers()

    t_off, timers_off = run("0")
    t_on, timers_on = run("1")
    _tree_bitwise(t_off, t_on, "resident")
    assert timers_on.get("tree_build_dispatches") == n_trees, timers_on
    assert timers_off.get("tree_build_dispatches") == \
        n_trees * (cfg.max_depth + 1), timers_off
    assert timers_on.get("host_syncs", 0) == 0, timers_on
    assert timers_off.get("host_syncs", 0) == 0, timers_off
