"""Model-health-plane tests (tier-1): the persistent metrics store
(disabled-path zero-file contract, restart survival, rollup
compaction), rolling drift parity against the one-shot `stats -psi`,
SLO transitions with hysteresis, and the acceptance drill — a `shifu
watch --monitor-only` tick over injected drift produces a breach
that is visible in the store, in `shifu health`, in `shifu top`, and
as `watch.*` spans in the merged trace — plus the chaos contract
(obs.metrics_flush / obs.alert / watch.window faults are absorbed).
"""

import glob
import json
import logging
import os

import numpy as np
import pandas as pd
import pytest

from shifu_tpu import resilience
from shifu_tpu.cli import main as cli_main
from shifu_tpu.obs.health import store as health_store
from shifu_tpu.obs.health.drift import RollingDrift
from shifu_tpu.obs.health.slo import SloEvaluator, load_slos
from shifu_tpu.processor.base import ProcessorContext


@pytest.fixture(autouse=True)
def _health_isolation(monkeypatch):
    """Every test starts with the metrics knob off and no inherited
    SLO/webhook config; a test that records does so explicitly."""
    for k in ("SHIFU_TPU_METRICS", "SHIFU_TPU_METRICS_ROLLUP",
              "SHIFU_TPU_SLO_FILE", "SHIFU_TPU_ALERT_WEBHOOK",
              "SHIFU_TPU_TRACE", "SHIFU_TPU_FAULT"):
        monkeypatch.delenv(k, raising=False)
    resilience.reset_faults()
    yield
    resilience.reset_faults()


def _tiny_model_set(tmp_path, n_rows=300, seed=7):
    # PRIVATE generator: the golden-file tests share the session rng
    # stream, and these fixtures must not shift it
    from tests.synth import make_model_set
    return make_model_set(tmp_path, np.random.default_rng(seed),
                          n_rows=n_rows)


def _raw_frame(model_set):
    dpath = os.path.join(model_set, "data", "part-00000")
    hpath = os.path.join(model_set, "data", ".pig_header")
    header = open(hpath).read().strip().split("|")
    return pd.read_csv(dpath, sep="|", names=header, dtype=str), header


def _shift_numerics(df, delta=5.0):
    """A drifted copy: every num_* value moves +delta (missing tokens
    kept), so the window's distribution piles into the top training
    bin → large PSI vs the frozen baseline."""
    out = df.copy()
    for col in out.columns:
        if not col.startswith("num_"):
            continue
        v = out[col].to_numpy(dtype=object).copy()
        for i, s in enumerate(v):
            try:
                v[i] = f"{float(s) + delta:.6f}"
            except (TypeError, ValueError):
                pass
        out[col] = v
    return out


# ---------------------------------------------------------------------------
# metrics store: disabled path, persistence, rollup
# ---------------------------------------------------------------------------

def test_disabled_path_writes_no_files_enabled_survives_restart(
        tmp_path, monkeypatch):
    root = str(tmp_path)
    st = health_store.MetricsStore(root)
    st.emit("serve.p99_ms", 12.5)
    st.counter("step.completed", step="stats")
    assert st.flush() == 0
    # the whole knob-off path is inert: no buffer, no directory
    assert not os.path.exists(os.path.join(root, "tmp", "metrics"))
    assert st.series("serve.p99_ms") == []

    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    st.emit("serve.p99_ms", 12.5, ts=100.0)
    st.emit("serve.p99_ms", 14.0, ts=101.0, model="nn")
    st.event("drift", features="num_0")
    assert st.flush() == 3
    assert os.path.exists(health_store.metrics_path(root))

    # a NEW store instance (process restart) reads the same history
    st2 = health_store.MetricsStore(root)
    assert st2.series("serve.p99_ms") == [(100.0, 12.5), (101.0, 14.0)]
    ev = st2.events(names=["drift"])
    assert len(ev) == 1 and ev[0]["tags"]["features"] == "num_0"
    pt = st2.read_points(names=["serve.p99_ms"])[1]
    # schema pinned by profiling.METRIC_FIELDS
    from shifu_tpu.profiling import METRIC_FIELDS
    assert tuple(pt) == METRIC_FIELDS
    assert pt["tags"] == {"model": "nn"}

    # the read path keeps working after the knob goes away (the
    # `shifu health` inspect-someone-else's-history contract)
    monkeypatch.delenv("SHIFU_TPU_METRICS")
    assert health_store.MetricsStore(root).series("serve.p99_ms") \
        == [(100.0, 12.5), (101.0, 14.0)]


def test_rollup_compacts_but_preserves_recent_queries(
        tmp_path, monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    monkeypatch.setenv("SHIFU_TPU_METRICS_ROLLUP", "1500")
    root = str(tmp_path)
    st = health_store.MetricsStore(root)
    base = 1_786_000_000.0
    n = 300
    for i in range(n):
        st.emit("serve.p99_ms", float(i), ts=base + 10.0 * i)
        if i % 25 == 0:
            st.flush()
    st.flush()

    path = health_store.metrics_path(root)
    pts = health_store.MetricsStore(root).read_points()
    by_kind = {}
    for p in pts:
        by_kind.setdefault(p["kind"], []).append(p)
    assert "rollup" in by_kind, "size bound never triggered compaction"
    # compacted: far fewer lines than points emitted
    assert sum(1 for _ in open(path)) < n

    # conservation: rollup counts + surviving raw points == everything
    # ever emitted (compaction aggregates, it never drops)
    total = sum(p["value"]["count"] for p in by_kind["rollup"]) \
        + len(by_kind["gauge"])
    assert total == n
    for p in by_kind["rollup"]:
        assert set(p["value"]) == {"count", "sum", "min", "max", "last"}

    # the recent window reads back verbatim and time-ordered, with the
    # newest RAW value last (a rollup may never shadow newer points)
    ser = health_store.MetricsStore(root).series("serve.p99_ms")
    ts = [t for t, _ in ser]
    assert ts == sorted(ts)
    assert ser[-1] == (base + 10.0 * (n - 1), float(n - 1))
    gauges = by_kind["gauge"]
    assert len(gauges) >= 8   # compaction must keep a raw tail
    raw_tail = [v for _, v in ser][-len(gauges):]
    assert raw_tail == [float(v) for v in range(n - len(gauges), n)]
    # every rollup is older than every surviving raw point, so a
    # since= window over the raw tail sees only raw points
    first_raw_ts = min(p["ts"] for p in gauges)
    assert all(p["ts"] <= first_raw_ts for p in by_kind["rollup"])
    recent = health_store.MetricsStore(root).read_points(
        names=["serve.p99_ms"], since=first_raw_ts)
    assert all(p["kind"] == "gauge" for p in recent)
    assert len(recent) == len(gauges)


# ---------------------------------------------------------------------------
# rolling drift: parity with the one-shot `stats -psi`
# ---------------------------------------------------------------------------

def test_rolling_psi_windows_reproduce_one_shot_cohort_psi(tmp_path):
    """Feed the one-shot PSI job's cohorts to RollingDrift as arriving
    windows: `mean_psi_vs_global()` must reproduce `columnStats.psi`
    (same counts, same float64 psi_metric) to 1e-8."""
    from shifu_tpu.config.column_config import load_column_configs

    model_set = _tiny_model_set(tmp_path, n_rows=1000, seed=11)
    # the test_psi month-cohort surgery: append a month column and
    # point psiColumnName at it
    df, header = _raw_frame(model_set)
    df["month"] = np.where(np.arange(len(df)) % 2 == 0, "m1", "m2")
    df.to_csv(os.path.join(model_set, "data", "part-00000"), sep="|",
              header=False, index=False)
    with open(os.path.join(model_set, "data", ".pig_header"), "w") as f:
        f.write("|".join(header + ["month"]) + "\n")
    mc_path = os.path.join(model_set, "ModelConfig.json")
    mc = json.load(open(mc_path))
    mc["stats"]["psiColumnName"] = "month"
    with open(mc["dataSet"]["metaColumnNameFile"], "a") as f:
        f.write("month\n")
    json.dump(mc, open(mc_path, "w"))

    for cmd in (["init"], ["stats"], ["stats", "-psi"]):
        assert cli_main(["--dir", model_set] + cmd) == 0

    ctx = ProcessorContext.load(model_set)
    drift = RollingDrift(ctx)
    full, _ = _raw_frame(model_set)
    for cohort in ("m1", "m2"):
        win = full[full["month"] == cohort].reset_index(drop=True)
        snap = drift.observe(win)
        assert snap["rows"] > 0 and snap["features"]
        # random even/odd cohorts vs the full-table baseline: no drift
        assert snap["psi_max"] < 0.05

    rolling = drift.mean_psi_vs_global()
    ccs = load_column_configs(os.path.join(model_set,
                                           "ColumnConfig.json"))
    compared = {"num": 0, "cat": 0}
    for cc in ccs:
        if cc.columnStats.psi is None or cc.columnName not in rolling:
            continue
        assert rolling[cc.columnName] == pytest.approx(
            cc.columnStats.psi, abs=1e-8), cc.columnName
        compared["cat" if cc.is_categorical else "num"] += 1
    assert compared["num"] >= 4 and compared["cat"] >= 2, compared


def test_drift_monitor_requires_frozen_bins(tmp_path):
    model_set = _tiny_model_set(tmp_path)
    assert cli_main(["--dir", model_set, "init"]) == 0
    with pytest.raises(ValueError, match="run `shifu stats` first"):
        RollingDrift(ProcessorContext.load(model_set))


def test_drift_monitor_flags_shifted_window(tmp_path):
    model_set = _tiny_model_set(tmp_path, n_rows=600, seed=13)
    for cmd in (["init"], ["stats"]):
        assert cli_main(["--dir", model_set] + cmd) == 0
    drift = RollingDrift(ProcessorContext.load(model_set))
    df, _ = _raw_frame(model_set)
    calm = drift.observe(df)
    assert calm["psi_max"] < 0.05 and calm["drifted"] == []
    hot = drift.observe(_shift_numerics(df))
    assert hot["psi_max"] > 0.25
    assert any(f.startswith("num_") for f in hot["drifted"])
    # categorical columns did not move
    assert not any(f.startswith("cat_") for f in hot["drifted"])


# ---------------------------------------------------------------------------
# SLO watchdog: classification, hysteresis, alert fan-out
# ---------------------------------------------------------------------------

_LAT_SLO = {"name": "lat", "metric": "serve.p99_ms", "op": "<=",
            "warn": 50.0, "breach": 200.0, "window_s": 3600.0,
            "agg": "last"}


def test_slo_transitions_hysteresis_and_sinks(tmp_path, monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    root = str(tmp_path)
    st = health_store.store(root)
    ev = SloEvaluator(root, slos=[dict(_LAT_SLO)], clear=2)
    seen = []
    ev.register_sink(seen.append)

    def tick(value):
        st.emit("serve.p99_ms", value)
        return ev.evaluate()[0]["state"]

    # no data → ok; absence of evidence never pages anyone
    assert ev.evaluate()[0]["state"] == "ok"
    assert tick(10.0) == "ok"
    # degrade IMMEDIATELY: one bad sample is a real warn/breach
    assert tick(120.0) == "warn"
    assert tick(500.0) == "breach"
    # recovery is damped: `clear`=2 consecutive better samples needed
    assert tick(10.0) == "breach"
    assert tick(10.0) == "ok"

    states = [r["state"] for r in ev.drain_transitions()]
    assert states == ["warn", "breach", "ok"]
    assert ev.drain_transitions() == []          # drained
    assert [r["state"] for r in seen] == states  # custom sink saw all
    from shifu_tpu.profiling import HEALTH_FIELDS
    assert set(HEALTH_FIELDS) <= set(seen[0])    # pinned record shape
    # the file sink persisted every transition next to the store
    alerts = os.path.join(root, "tmp", "metrics", "alerts.jsonl")
    recs = [json.loads(l) for l in open(alerts) if l.strip()]
    assert [r["state"] for r in recs] == states
    # every evaluation left a health.<slo> gauge rank series
    ranks = [v for _, v in st.series("health.lat")]
    assert ranks == [0.0, 0.0, 1.0, 2.0, 2.0, 0.0]


def test_slo_larger_is_better_orientation(tmp_path, monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    root = str(tmp_path)
    st = health_store.store(root)
    auc = {"name": "auc", "metric": "eval.auc", "op": ">=",
           "warn": 0.75, "breach": 0.70, "window_s": 3600.0}
    ev = SloEvaluator(root, slos=[auc], clear=1)
    for value, want in ((0.9, "ok"), (0.72, "warn"), (0.6, "breach")):
        st.emit("eval.auc", value)
        assert ev.evaluate()[0]["state"] == want, value


def test_slo_file_precedence(tmp_path, monkeypatch):
    root = str(tmp_path)
    defaults = load_slos(root)
    assert {s["name"] for s in defaults} >= {"serve_p99", "drift", "auc"}
    with open(os.path.join(root, "slo.json"), "w") as f:
        json.dump({"slos": [dict(_LAT_SLO)]}, f)
    assert [s["name"] for s in load_slos(root)] == ["lat"]
    other = tmp_path / "override.json"
    other.write_text(json.dumps([dict(_LAT_SLO, name="ovr")]))
    monkeypatch.setenv("SHIFU_TPU_SLO_FILE", str(other))
    assert [s["name"] for s in load_slos(root)] == ["ovr"]
    # malformed rules are rejected loudly, not half-loaded
    other.write_text(json.dumps([{"name": "x", "metric": "m"}]))
    with pytest.raises(ValueError, match="missing"):
        load_slos(root)


# ---------------------------------------------------------------------------
# acceptance drill: watch tick over injected drift → breach everywhere
# ---------------------------------------------------------------------------

def test_watch_drill_breach_visible_in_health_top_and_trace(
        tmp_path, monkeypatch, capsys, caplog):
    model_set = _tiny_model_set(tmp_path)
    for cmd in (["init"], ["stats"]):
        assert cli_main(["--dir", model_set] + cmd) == 0

    # AFTER stats froze the bins, the arriving data shifts: rewrite the
    # dataPath so the watch loop's first window is drifted production
    # traffic vs the frozen training baseline
    df, _ = _raw_frame(model_set)
    _shift_numerics(df).to_csv(
        os.path.join(model_set, "data", "part-00000"), sep="|",
        header=False, index=False)
    with open(os.path.join(model_set, "slo.json"), "w") as f:
        json.dump({"slos": [
            {"name": "drift", "metric": "drift.psi_max", "op": "<=",
             "warn": 0.05, "breach": 0.2, "window_s": 86400.0,
             "agg": "last"}]}, f)

    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    with caplog.at_level(logging.WARNING):
        assert cli_main(["--dir", model_set, "watch", "--monitor-only",
                         "--iterations", "1", "--interval-s", "0"]) == 0
    monkeypatch.delenv("SHIFU_TPU_TRACE")
    # monitor-only leaves the retrain loop open and says so
    assert "no refresh controller attached" in caplog.text

    # 1. persisted: drift + breach events and the psi gauge on DISK
    # (a fresh store instance — restart-visible, not buffer state)
    st = health_store.MetricsStore(model_set)
    names = {e["name"] for e in st.events(limit=20)}
    assert {"event.drift", "event.breach"} <= names
    assert st.series("drift.psi_max")[-1][1] > 0.2
    alerts = os.path.join(model_set, "tmp", "metrics", "alerts.jsonl")
    assert any(json.loads(l)["state"] == "breach"
               for l in open(alerts) if l.strip())

    # 2. `shifu health`: breach status (exit 1), the rule, the events
    monkeypatch.delenv("SHIFU_TPU_METRICS")   # read path needs no knob
    capsys.readouterr()
    assert cli_main(["--dir", model_set, "health"]) == 1
    out = capsys.readouterr().out
    assert "status: BREACH" in out
    assert "drift.psi_max" in out and "recent events:" in out

    # 3. `shifu top`: the health/drift event tail renders
    assert cli_main(["--dir", model_set, "top"]) == 0
    out = capsys.readouterr().out
    assert "health/drift events:" in out and "event.breach" in out

    # 4. the watch tick was span-traced into the merged trace
    merged = glob.glob(os.path.join(model_set, "tmp", "trace",
                                    "*.trace.json"))
    assert len(merged) == 1
    events = json.load(open(merged[0]))["traceEvents"]
    spans = {e["name"] for e in events}
    assert {"watch.window", "watch.evaluate"} <= spans
    win = next(e for e in events if e["name"] == "watch.window")
    assert win["args"]["rows"] == len(df)


def test_watch_full_mode_routes_breach_to_refresh(tmp_path, monkeypatch):
    """`shifu watch` (no --monitor-only) attaches a RefreshController
    and a breach lands in its handle_breach — the loop is closed."""
    from shifu_tpu.obs.health import refresh as refresh_mod

    model_set = _tiny_model_set(tmp_path)
    for cmd in (["init"], ["stats"]):
        assert cli_main(["--dir", model_set] + cmd) == 0
    df, _ = _raw_frame(model_set)
    _shift_numerics(df).to_csv(
        os.path.join(model_set, "data", "part-00000"), sep="|",
        header=False, index=False)
    with open(os.path.join(model_set, "slo.json"), "w") as f:
        json.dump({"slos": [
            {"name": "drift", "metric": "drift.psi_max", "op": "<=",
             "warn": 0.05, "breach": 0.2, "window_s": 86400.0,
             "agg": "last"}]}, f)
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    calls = []
    monkeypatch.setattr(
        refresh_mod.RefreshController, "handle_breach",
        lambda self, rec: calls.append(rec) or "promoted")
    noted = []
    monkeypatch.setattr(
        refresh_mod.RefreshController, "note_window",
        lambda self, w: noted.append(len(w)))
    assert cli_main(["--dir", model_set, "watch",
                     "--iterations", "1", "--interval-s", "0"]) == 0
    assert calls and calls[0]["state"] == "breach"
    # every observed window also fed the controller as retrain fodder
    assert noted == [len(df)]


# ---------------------------------------------------------------------------
# chaos: health-plane faults are absorbed, never fatal
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site", ["obs.metrics_flush", "obs.alert",
                                  "watch.window"])
def test_health_plane_faults_absorbed(tmp_path, monkeypatch, site):
    from shifu_tpu.obs.health import watch as watch_mod

    model_set = _tiny_model_set(tmp_path)
    for cmd in (["init"], ["stats"]):
        assert cli_main(["--dir", model_set] + cmd) == 0
    df, _ = _raw_frame(model_set)
    with open(os.path.join(model_set, "slo.json"), "w") as f:
        json.dump({"slos": [
            {"name": "drift", "metric": "drift.psi_max", "op": "<=",
             "warn": 0.05, "breach": 0.2, "window_s": 86400.0}]}, f)

    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    monkeypatch.setenv("SHIFU_TPU_FAULT", f"{site}:oserror:1")
    resilience.reset_faults()
    ctx = ProcessorContext.load(model_set)
    rc = watch_mod.run_monitor(ctx, interval_s=0.0, iterations=1,
                               windows=[_shift_numerics(df)])
    assert rc == 0, f"{site}: monitor must absorb the fault"
    monkeypatch.delenv("SHIFU_TPU_FAULT")
    resilience.reset_faults()

    st = health_store.MetricsStore(model_set)
    if site == "watch.window":
        # the window was skipped (counted), drift never computed — and
        # the monitor lived to flush the skip counter
        assert st.series("watch.window_failed") != []
        assert st.series("drift.psi_max") == []
    else:
        # the drift window itself survived; a flush retry (rebuffered
        # points) / the surviving sinks carried the evidence to disk
        assert st.series("drift.psi_max")[-1][1] > 0.2
        assert {e["name"] for e in st.events(limit=20)} >= \
            {"event.drift", "event.breach"}
    if site == "obs.alert":
        # one sink dispatch died; the OTHERS still fired (per-sink
        # absorption) — the file sink's record reached disk
        alerts = os.path.join(model_set, "tmp", "metrics",
                              "alerts.jsonl")
        assert os.path.exists(alerts)


# ---------------------------------------------------------------------------
# bench-history regression gate (tools/bench_regress.py)
# ---------------------------------------------------------------------------

def _bench_log(tmp_path, *recs):
    path = tmp_path / "bench.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(path)


def test_bench_regress_flags_drop_and_bound_flip(tmp_path):
    import importlib
    br = importlib.import_module("tools.bench_regress")

    def rec(ts, tput, bound=None):
        r = {"task": "nn", "backend": "tpu", "ts": ts,
             "row_epochs_per_sec": tput}
        if bound:
            r["roofline"] = {"bound": bound}
        return r

    # newest holds within threshold → clean
    log = _bench_log(tmp_path, rec(1, 100.0), rec(2, 110.0),
                     rec(3, 95.0))
    assert br.main(["--log", log]) == 0
    # newest drops >20% below the trailing median → finding
    log = _bench_log(tmp_path, rec(1, 100.0), rec(2, 110.0),
                     rec(3, 70.0))
    assert br.main(["--log", log]) == 1
    # throughput held but the roofline bound flipped → finding
    log = _bench_log(tmp_path, rec(1, 100.0, "compute"),
                     rec(2, 102.0, "compute"), rec(3, 101.0, "memory"))
    assert br.main(["--log", log]) == 1
    # a single trailing record is not a baseline; absent log is clean
    log = _bench_log(tmp_path, rec(1, 100.0), rec(2, 10.0))
    assert br.main(["--log", log]) == 0
    assert br.main(["--log", str(tmp_path / "absent.jsonl")]) == 0


# ---------------------------------------------------------------------------
# webhook alert sink: a REAL bounded-timeout HTTP POST, retried through
# the obs.webhook site, absorbed by the alert fan-out when dead
# ---------------------------------------------------------------------------

def _webhook_server():
    import http.server
    import threading
    received = []

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0"))
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *_a):   # keep pytest output quiet
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), _Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, received


def test_webhook_sink_posts_and_retries_through_fault(monkeypatch):
    """The sink delivers the breach record to a live receiver, and a
    transient fault at the obs.webhook site is retried away — the
    POST still lands."""
    from shifu_tpu.obs.health import slo as slo_mod
    srv, received = _webhook_server()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}/alert"
        monkeypatch.setenv("SHIFU_TPU_ALERT_WEBHOOK", url)
        monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.01")
        slo_mod.webhook_sink({"slo": "drift", "state": "breach",
                              "value": 0.41})
        assert received and received[-1]["slo"] == "drift"
        monkeypatch.setenv("SHIFU_TPU_FAULT", "obs.webhook:oserror:1")
        resilience.reset_faults()
        slo_mod.webhook_sink({"slo": "auc", "state": "warn"})
        assert received[-1]["slo"] == "auc"
        assert len(received) == 2   # retry did not double-deliver
    finally:
        srv.shutdown()


def test_dead_webhook_never_fails_the_watch_tick(tmp_path, monkeypatch,
                                                 caplog):
    """Nothing listens on the configured port: the bounded timeout +
    retry budget exhausts, the failure raises out of the sink, and the
    alert fan-out ABSORBS it — the transition still reaches the other
    sinks (alerts.jsonl) and the caller never sees an error."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    monkeypatch.setenv("SHIFU_TPU_ALERT_WEBHOOK",
                       f"http://127.0.0.1:{port}/alert")
    monkeypatch.setenv("SHIFU_TPU_ALERT_WEBHOOK_TIMEOUT_S", "0.2")
    monkeypatch.setenv("SHIFU_TPU_RETRY_ATTEMPTS", "2")
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.01")
    root = str(tmp_path)
    ev = SloEvaluator(root, slos=[], clear=1)
    with caplog.at_level(logging.WARNING):
        ev.alert({"slo": "lat", "state": "breach", "value": 999.0})
    assert "webhook_sink" in caplog.text and "absorbed" in caplog.text
    alerts = os.path.join(root, "tmp", "metrics", "alerts.jsonl")
    recs = [json.loads(l) for l in open(alerts) if l.strip()]
    assert recs and recs[-1]["slo"] == "lat"


def test_bench_regress_gates_refresh_invariants(tmp_path):
    """The refresh record's gates are absolute (no trailing history
    needed): swap cheaper than re-warm, zero swap compile misses,
    guardrail verdict promote."""
    import importlib
    br = importlib.import_module("tools.bench_regress")

    def rec(**kw):
        r = {"task": "refresh", "backend": "cpu", "ts": 1,
             "breach_to_promoted_s": 30.0, "swap_s": 0.01,
             "rewarm_s": 1.2, "swap_compile_misses": 0,
             "guardrail": {"decision": "promote"}}
        r.update(kw)
        return r

    assert br.main(["--log", _bench_log(tmp_path, rec())]) == 0
    assert br.main(["--log", _bench_log(
        tmp_path, rec(swap_s=2.0))]) == 1           # lost to re-warm
    assert br.main(["--log", _bench_log(
        tmp_path, rec(swap_compile_misses=3))]) == 1  # swap recompiled
    assert br.main(["--log", _bench_log(
        tmp_path, rec(guardrail={"decision": "hold"}))]) == 1
