"""Durable streaming ingest plane (``data/ingest.py``) — the
partitioned row log and its exactly-once window contract:

- round trips: append → seal (by row count and by age) → window reads
  in deterministic order, with the consumer offset committing only on
  an explicit `commit` — an uncommitted window REPLAYS bitwise;
- durability: reopen, `read_range` over any committed range is
  byte-identical forever (immutable segments), a truncated segment is
  refused loudly, no dot-temp residue anywhere;
- the fsspec twin: the same contract over a `memory://` log root;
- the legacy dataPath tail's line-atomicity regression (a slow writer
  mid-append never delivers a torn row — satellite of the ingest PR);
- the acceptance drill: shifted rows appended to the log → `shifu
  watch --ingest` drift breach → refresh retrains on the committed
  window → the promoted manifest records the exact (segment, offset)
  range and `read_range` re-reads the training bytes exactly.

SIGKILL crash drills for the ``ingest.*`` fault sites live in
``tests/test_chaos.py``; the 2-process sharded-writer drill in
``tests/test_multihost.py``.
"""

import hashlib
import json
import os
import shutil

import numpy as np
import pytest

from shifu_tpu import registry, resilience
from shifu_tpu.cli import main as cli_main
from shifu_tpu.data.ingest import (REFRESH_CONSUMER, WATCH_CONSUMER,
                                   RowLog, frame_from_rows,
                                   rows_from_frame)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _ingest_isolation(monkeypatch):
    for k in ("SHIFU_TPU_METRICS", "SHIFU_TPU_SLO_FILE", "SHIFU_TPU_FAULT",
              "SHIFU_TPU_INGEST_SEGMENT_ROWS",
              "SHIFU_TPU_INGEST_SEGMENT_AGE_S"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.01")
    resilience.reset_faults()
    yield
    resilience.reset_faults()


def _batch(n=10, tag=""):
    return [f"{i}|v{tag}{i}" for i in range(n)]


def _no_tmp_residue(root):
    return [os.path.join(d, f) for d, _dirs, fs in os.walk(root)
            for f in fs if f.startswith(".tmp.")]


def _sha(lines):
    return hashlib.sha256("\n".join(lines).encode("utf-8")).hexdigest()


# ---------------------------------------------------------------------------
# the log itself
# ---------------------------------------------------------------------------

def test_round_trip_exactly_once_and_replay(tmp_path):
    root = str(tmp_path / "log")
    lg = RowLog(root, header=["a", "b"], segment_rows=4)
    lg.append(_batch(10))
    lg.seal_all()
    assert lg.sealed_rows() == 10 and lg.open_rows() == 0

    # an uncommitted window REPLAYS bitwise — reading moves nothing
    w1 = lg.read_window(WATCH_CONSUMER)
    w2 = lg.read_window(WATCH_CONSUMER)
    assert w1.lines == w2.lines == _batch(10)
    assert w1.start == w2.start and w1.end == w2.end
    assert lg.lag(WATCH_CONSUMER) == 10

    # commit moves exactly to the window's end; the next read is empty
    lg.commit(WATCH_CONSUMER, w1.end)
    assert lg.lag(WATCH_CONSUMER) == 0
    assert lg.consumed_rows(WATCH_CONSUMER) == 10
    assert lg.read_window(WATCH_CONSUMER) is None

    # consumers are independent: a second one still sees everything
    w3 = lg.read_window("eval")
    assert w3.lines == _batch(10)

    # max_rows caps the window and the remainder stays for next tick
    lg.append(_batch(6, tag="x"))
    lg.seal_all()
    w4 = lg.read_window(WATCH_CONSUMER, max_rows=4)
    assert len(w4.lines) == 4
    lg.commit(WATCH_CONSUMER, w4.end)
    w5 = lg.read_window(WATCH_CONSUMER)
    assert w4.lines + w5.lines == _batch(6, tag="x")
    assert not _no_tmp_residue(root)


def test_seal_by_age_bounds_trickle_staleness(tmp_path):
    import time as _time
    lg = RowLog(str(tmp_path / "log"), header=["a", "b"],
                segment_rows=10_000, segment_age_s=0.05)
    lg.append(["1|one"])
    # nowhere near the row threshold and still young: stays buffered
    assert lg.sealed_rows() == 0 and lg.open_rows() == 1
    _time.sleep(0.06)
    # the NEXT append finds the open segment over age and seals it —
    # a slow trickle can never keep rows invisible to readers forever
    lg.append(["2|two"])
    assert lg.sealed_rows() == 2 and lg.open_rows() == 0
    w = lg.read_window(WATCH_CONSUMER)
    assert w.lines == ["1|one", "2|two"]


def test_reopen_and_committed_range_reads_bitwise_forever(tmp_path):
    root = str(tmp_path / "log")
    lg = RowLog(root, header=["a", "b"], partitions=2, segment_rows=3)
    lg.append(_batch(11))
    lg.seal_all()
    start = lg.committed_offset(WATCH_CONSUMER)
    w = lg.read_window(WATCH_CONSUMER)
    lg.commit(WATCH_CONSUMER, w.end)
    d0 = _sha(w.lines)

    # a FRESH handle (reopen: header/delimiter come from log.json)
    lg2 = RowLog(root)
    assert lg2.header == ["a", "b"] and lg2.delimiter == "|"
    assert _sha(lg2.read_range(start, w.end)) == d0

    # ... and the range stays byte-identical after the log GROWS
    lg2.append(_batch(5, tag="later"))
    lg2.seal_all()
    assert _sha(RowLog(root).read_range(start, w.end)) == d0
    assert not _no_tmp_residue(root)


def test_multi_partition_order_is_deterministic(tmp_path):
    root = str(tmp_path / "log")
    lg = RowLog(root, header=["a", "b"], partitions=3, segment_rows=2)
    rows = _batch(13)
    for r in rows:
        lg.append([r])
    lg.seal_all()
    w1 = RowLog(root).read_window(WATCH_CONSUMER)
    w2 = RowLog(root).read_window(WATCH_CONSUMER)
    # identical across handles (partitions ascending, segments
    # ascending) and nothing lost or duplicated across partitions
    assert w1.lines == w2.lines
    assert sorted(w1.lines) == sorted(rows)


def test_truncated_segment_is_refused_loudly(tmp_path):
    root = str(tmp_path / "log")
    lg = RowLog(root, header=["a", "b"], segment_rows=4)
    lg.append(_batch(4))
    lg.seal_all()
    seg = os.path.join(root, "part-0", "seg-000001.rows")
    with open(seg, encoding="utf-8") as f:
        content = f.read()
    with open(seg, "w", encoding="utf-8") as f:
        f.write(content.splitlines(True)[0])   # 1 row where 4 promised
    with pytest.raises(RuntimeError, match="corrupt"):
        RowLog(root).read_window(WATCH_CONSUMER)


def test_frame_round_trip_preserves_missing_tokens():
    import pandas as pd
    df = pd.DataFrame({"a": ["1.5", "", "x"], "b": ["", "?", "z"]})
    lines = rows_from_frame(df, "|")
    assert lines == ["1.5|", "|?", "x|z"]
    back = frame_from_rows(lines, ["a", "b"], "|")
    assert back.values.tolist() == df.values.tolist()


def test_memory_fsspec_twin_round_trips(tmp_path):
    pytest.importorskip("fsspec")
    root = "memory://ingest_twin/log"
    lg = RowLog(root, header=["a", "b"], segment_rows=4)
    lg.append(_batch(9))
    lg.seal_all()
    start = lg.committed_offset(WATCH_CONSUMER)
    w = lg.read_window(WATCH_CONSUMER)
    assert w.lines == _batch(9)
    lg.commit(WATCH_CONSUMER, w.end)
    # reopen over the remote scheme: offsets, ranges, inventory
    lg2 = RowLog(root)
    assert lg2.lag(WATCH_CONSUMER) == 0
    assert _sha(lg2.read_range(start, w.end)) == _sha(w.lines)
    inv = lg2.inventory()
    assert inv["sealed_rows"] == 9
    assert inv["consumers"][0]["lag_rows"] == 0


# ---------------------------------------------------------------------------
# legacy tail: line-atomicity regression (torn-final-line race)
# ---------------------------------------------------------------------------

def test_legacy_tail_never_delivers_a_torn_row(tmp_path):
    """A slow writer mid-append (bytes flushed up to the middle of a
    row, no newline yet) must NOT surface a torn row: the tail
    consumes only up to the last newline and carries the partial into
    the tick where the writer finishes it."""
    from shifu_tpu.obs.health.watch import _production_window
    from shifu_tpu.processor.base import ProcessorContext
    from tests.synth import make_model_set

    ms = make_model_set(tmp_path, np.random.default_rng(5), n_rows=60)
    assert cli_main(["--dir", ms, "init"]) == 0
    ctx = ProcessorContext.load(ms)
    part = os.path.join(ms, "data", "part-00000")
    template = open(part, encoding="utf-8").readline().strip()

    # tick 1 consumes the whole existing table (ends in a newline)
    tail = {}
    df, tail = _production_window(ctx, tail)
    base_rows = len(df)
    assert base_rows == 48   # the 80% training split of 60 rows

    # the slow writer lands one complete row and HALF of the next
    half = len(template) // 2
    with open(part, "a", encoding="utf-8") as f:
        f.write(template + "\n" + template[:half])
        f.flush()
    df, tail = _production_window(ctx, tail)
    assert df is not None and len(df) == 1   # the torn row held back
    assert list(df.iloc[0]) == template.split("|")

    # nothing new completed → no window, cursor still parked before
    # the partial
    df, tail = _production_window(ctx, tail)
    assert df is None

    # the writer finishes the row (plus one more): both arrive WHOLE
    with open(part, "a", encoding="utf-8") as f:
        f.write(template[half:] + "\n" + template + "\n")
        f.flush()
    df, tail = _production_window(ctx, tail)
    assert df is not None and len(df) == 2
    assert list(df.iloc[0]) == template.split("|")
    assert list(df.iloc[1]) == template.split("|")


# ---------------------------------------------------------------------------
# acceptance drill: log → watch --ingest → breach → refresh → audit
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def trained_set(tmp_path_factory):
    """ONE trained tiny model set for the module (private rng — the
    golden-file tests share the session stream); tests copy it."""
    from tests.synth import make_model_set
    base = tmp_path_factory.mktemp("ingest_base")
    ms = make_model_set(base, np.random.default_rng(23), n_rows=400)
    cfg_path = os.path.join(ms, "ModelConfig.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    cfg["train"]["numTrainEpochs"] = 8
    with open(cfg_path, "w") as f:
        json.dump(cfg, f, indent=2)
    for cmd in ("init", "stats", "norm", "train"):
        assert cli_main(["--dir", ms, cmd]) == 0, cmd
    return ms


def _clone_set(trained_set, tmp_path):
    ms = os.path.join(str(tmp_path), "ModelSet")
    shutil.copytree(trained_set, ms)
    return ms


def _shifted_rows(trained_set, delta=0.5):
    import pandas as pd
    hdr = open(os.path.join(trained_set, "data",
                            ".pig_header")).read().strip().split("|")
    df = pd.read_csv(os.path.join(trained_set, "data", "part-00000"),
                     sep="|", names=hdr, dtype=str,
                     keep_default_na=False)
    for col in df.columns:
        if not col.startswith("num_"):
            continue
        v = df[col].to_numpy(dtype=object).copy()
        for i, s in enumerate(v):
            try:
                v[i] = f"{float(s) + delta:.6f}"
            except (TypeError, ValueError):
                pass
        df[col] = v
    return hdr, rows_from_frame(df, "|")


def _drift_slo(ms):
    with open(os.path.join(ms, "slo.json"), "w") as f:
        json.dump({"slos": [
            {"name": "drift", "metric": "drift.psi_max", "op": "<=",
             "warn": 0.02, "breach": 0.05, "window_s": 86400.0,
             "agg": "last"}]}, f)


def test_watch_ingest_breach_refresh_records_auditable_range(
        trained_set, tmp_path, monkeypatch):
    """The whole plane, end to end: drifted rows appended to the row
    log, ONE `watch --ingest` tick reads the committed window → PSI
    breach → the refresh controller retrains on ITS OWN committed
    window read → the promoted manifest records the exact (segment,
    offset) range — and `read_range` over that recorded range re-reads
    the challenger's training bytes exactly."""
    from shifu_tpu.obs.health import watch as watch_mod
    from shifu_tpu.obs.health.refresh import RefreshController
    from shifu_tpu.processor.base import ProcessorContext

    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    ms = _clone_set(trained_set, tmp_path)
    reg = os.path.join(str(tmp_path), "reg")
    v1 = registry.publish(reg, "m", os.path.join(ms, "models"),
                          ladder=(1, 4))
    _drift_slo(ms)

    hdr, shifted = _shifted_rows(trained_set)
    root = str(tmp_path / "rowlog")
    lg = RowLog(root, header=hdr, segment_rows=128)
    lg.append(shifted)
    lg.seal_all()

    ctx = ProcessorContext.load(ms)
    ctl = RefreshController(ctx, registry_root=reg, model_name="m",
                            tolerance=0.2, cooldown_s=0.0,
                            ingest_log=lg)
    rc = watch_mod.run_monitor(ctx, interval_s=0.0, iterations=1,
                               refresh=ctl, ingest_log=lg)
    assert rc == 0
    assert ctl.last_outcome == "promoted", ctl.stats()
    assert registry.head(reg, "m") == "v002"

    # the manifest names the exact training window in log coordinates
    _, _, man = registry.resolve(reg, "m")
    assert man["refresh"]["refreshed_from"] == v1
    iw = man["refresh"]["ingest_window"]
    assert iw["log"] == root and iw["rows"] == len(shifted)

    # audit: the recorded range re-reads the promoted model's actual
    # training bytes, and does so identically through a fresh handle
    replay = RowLog(root).read_range(iw["start"], iw["end"])
    wdir = os.path.join(ms, "tmp", "refresh", "run0001", "window")
    trained_on = [l.rstrip("\n") for l in
                  open(os.path.join(wdir, "part-00000"),
                       encoding="utf-8")]
    assert replay == trained_on == shifted
    assert _sha(RowLog(root).read_range(iw["start"], iw["end"])) \
        == _sha(replay)

    # both consumers committed exactly once — nothing skipped, nothing
    # left to replay
    assert lg.lag(WATCH_CONSUMER) == 0
    assert lg.lag(REFRESH_CONSUMER) == 0
    assert not _no_tmp_residue(root) and not _no_tmp_residue(reg)


def test_cli_watch_ingest_and_inventory(tmp_path, monkeypatch, capsys):
    """The CLI plumbing: `shifu watch --ingest <log> --monitor-only`
    consumes the drifted window from the log (breach lands in the
    store, offset commits), and `shifu ingest ls` reports the drained
    consumer at zero lag."""
    from shifu_tpu.obs.health import store as health_store
    from tests.synth import make_model_set

    ms = make_model_set(tmp_path, np.random.default_rng(9), n_rows=300)
    for cmd in ("init", "stats"):
        assert cli_main(["--dir", ms, cmd]) == 0
    _drift_slo(ms)

    hdr, shifted = _shifted_rows(ms, delta=5.0)
    root = str(tmp_path / "rowlog")
    lg = RowLog(root, header=hdr, segment_rows=64)
    lg.append(shifted)
    lg.seal_all()

    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    assert cli_main(["--dir", ms, "watch", "--monitor-only",
                     "--ingest", root,
                     "--iterations", "1", "--interval-s", "0"]) == 0
    st = health_store.MetricsStore(ms)
    assert st.series("drift.psi_max")[-1][1] > 0.05
    names = {e["name"] for e in st.events(limit=20)}
    assert {"event.drift", "event.breach"} <= names

    capsys.readouterr()
    assert cli_main(["--dir", ms, "ingest", "ls", "--log", root]) == 0
    inv = json.loads(capsys.readouterr().out)
    assert inv["sealed_rows"] == len(shifted)
    watch_row = next(c for c in inv["consumers"]
                     if c["name"] == WATCH_CONSUMER)
    assert watch_row["lag_rows"] == 0
    assert watch_row["committed_rows"] == len(shifted)
