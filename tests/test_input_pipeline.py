"""Async host input pipeline (`data/pipeline.py`) — ordering, queue
bounding, fault propagation, the `SHIFU_TPU_PREFETCH_WORKERS=0`
sequential fallback, and byte-identical async-vs-sync end-to-end runs
of the streaming stats/norm/train/eval paths. Plus the satellites that
ride on the same PR: retry counters surfaced per site, the remote
(fsspec) twin of `atomic_write`, and RESUME manifests for
varselect/train/export."""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

from shifu_tpu import resilience
from shifu_tpu.data import pipeline as pipe


@pytest.fixture(autouse=True)
def _fresh_pipeline(monkeypatch):
    """Each test owns the process-wide fault counters, stage timers and
    retry stats; none may leak into the tier-1 neighbours."""
    monkeypatch.delenv("SHIFU_TPU_FAULT", raising=False)
    monkeypatch.delenv("SHIFU_TPU_PREFETCH_DEPTH", raising=False)
    monkeypatch.delenv("SHIFU_TPU_PREFETCH_WORKERS", raising=False)
    resilience.reset_faults()
    resilience.reset_retry_stats()
    pipe.drain_stage_timers()
    yield
    resilience.reset_faults()
    resilience.reset_retry_stats()
    pipe.drain_stage_timers()


def _pipeline_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(("shifu-prefetch", "shifu-pipeline"))
            and t.is_alive()]


def _wait_no_pipeline_threads(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _pipeline_threads():
            return
        time.sleep(0.01)
    raise AssertionError(f"pipeline threads still alive: "
                         f"{_pipeline_threads()}")


# ---------------------------------------------------------------------------
# prefetch(iterable)
# ---------------------------------------------------------------------------

def test_prefetch_preserves_order_and_identity():
    items = [np.arange(i + 1) for i in range(11)]
    out = list(pipe.prefetch(iter(items), depth=2))
    assert len(out) == len(items)
    for got, want in zip(out, items):
        assert got is want  # same objects, exact source order


def test_prefetch_stays_bounded_depth_ahead():
    produced = []

    def src():
        for i in range(20):
            produced.append(i)
            yield i

    depth = 2
    max_ahead = 0
    consumed = 0
    for item in pipe.prefetch(src(), depth=depth):
        assert item == consumed
        time.sleep(0.02)  # give the producer every chance to run ahead
        # consumer holds 1 (current), queue holds <= depth, producer
        # may hold 1 more it is waiting to enqueue
        max_ahead = max(max_ahead, len(produced) - consumed)
        consumed += 1
    assert consumed == 20
    assert max_ahead <= depth + 2
    assert max_ahead < 20  # it did NOT slurp the whole source eagerly


def test_prefetch_workers_zero_restores_sequential_path(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_PREFETCH_WORKERS", "0")
    produced = []

    def src():
        for i in range(6):
            produced.append(i)
            yield i

    consumed = 0
    for item in pipe.prefetch(src()):
        assert not _pipeline_threads(), "sync path must not spawn threads"
        consumed += 1
        # strictly lazy: nothing is fetched ahead of the consumer
        assert len(produced) == consumed
    assert consumed == 6


def test_prefetch_fault_propagates_without_deadlock(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_FAULT", "pipeline.fetch:oserror:3")
    resilience.reset_faults()
    got = []
    with pytest.raises(OSError):
        for item in pipe.prefetch(iter(range(10)), depth=2):
            got.append(item)
    assert got == [0, 1]  # chunks before the injected 3rd fetch arrive
    _wait_no_pipeline_threads()


def test_prefetch_early_close_shuts_worker_down():
    def src():
        for i in range(1000):
            yield i

    for item in pipe.prefetch(src(), depth=2):
        if item == 3:
            break
    _wait_no_pipeline_threads()


def test_prefetch_overlap_stall_below_parse():
    """The acceptance number: with real overlap, consumer stall must sit
    strictly below total producer parse time."""
    pipe.drain_stage_timers()

    def slow_src():
        for i in range(8):
            time.sleep(0.02)  # "parse"
            yield i

    n = 0
    for _ in pipe.prefetch(slow_src(), depth=2):
        time.sleep(0.025)  # "device step" the parse should hide behind
        n += 1
    assert n == 8
    stages = pipe.drain_stage_timers()
    assert stages["chunks"] == 8
    assert stages["input_stall_s"] < stages["host_parse_s"]


def test_sync_fallback_counts_fetch_as_stall():
    pipe.drain_stage_timers()
    list(pipe.prefetch(iter(range(5)), depth=0))
    stages = pipe.drain_stage_timers()
    # all fetch time is on the critical path by definition
    assert stages["input_stall_s"] == stages["host_parse_s"]
    assert stages["chunks"] == 5


# ---------------------------------------------------------------------------
# map_prefetch(fn, items)
# ---------------------------------------------------------------------------

def test_map_prefetch_order_and_inflight_bound():
    lock = threading.Lock()
    inflight = {"now": 0, "max": 0}

    def fn(i):
        with lock:
            inflight["now"] += 1
            inflight["max"] = max(inflight["max"], inflight["now"])
        time.sleep(0.01)
        with lock:
            inflight["now"] -= 1
        return i * i

    depth = 3
    out = list(pipe.map_prefetch(fn, range(12), depth=depth, workers=3))
    assert out == [i * i for i in range(12)]
    assert inflight["max"] <= depth
    _wait_no_pipeline_threads()


def test_map_prefetch_error_at_position():
    def fn(i):
        if i == 3:
            raise ValueError("bad item")
        return i

    got = []
    with pytest.raises(ValueError, match="bad item"):
        for x in pipe.map_prefetch(fn, range(8), depth=2, workers=2):
            got.append(x)
    assert got == [0, 1, 2]  # error surfaces at the failed item's slot


def test_map_prefetch_workers_zero_sequential(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_PREFETCH_WORKERS", "0")
    # earlier tests' daemon workers may still be draining on a loaded
    # machine — this test asserts WE spawn none, so settle first
    _wait_no_pipeline_threads()
    seen_threads = []
    out = []
    for x in pipe.map_prefetch(lambda i: i + 100, range(5)):
        seen_threads.extend(_pipeline_threads())
        out.append(x)
    assert out == [100, 101, 102, 103, 104]
    assert not seen_threads


def test_map_prefetch_fault_injection(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_FAULT", "pipeline.fetch:oserror:2")
    resilience.reset_faults()
    with pytest.raises(OSError):
        list(pipe.map_prefetch(lambda i: i, range(6), depth=2, workers=2))
    _wait_no_pipeline_threads()


# ---------------------------------------------------------------------------
# end-to-end: async run is byte-identical to the sequential run
# ---------------------------------------------------------------------------

def _build_root(tmp_path, name, seed):
    """Two roots built from the same seed carry identical raw bytes."""
    from tests.synth import make_model_set
    rng = np.random.default_rng(seed)
    sub = tmp_path / name
    sub.mkdir()
    root = make_model_set(sub, rng, n_rows=2000,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [8],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM",
                                        "ChunkRows": 250})
    mc_path = os.path.join(root, "ModelConfig.json")
    with open(mc_path) as f:
        mc = json.load(f)
    mc["train"]["trainOnDisk"] = True
    mc["train"]["numTrainEpochs"] = 5
    with open(mc_path, "w") as f:
        json.dump(mc, f, indent=2)
    return root


def _run_flow(root):
    from shifu_tpu.cli import main as cli_main
    for cmd in (["init"], ["stats"], ["norm"], ["train"], ["eval"]):
        assert cli_main(["--dir", root] + cmd) == 0, f"{cmd} failed"


def _dir_file_bytes(path):
    out = {}
    for dirpath, _dirs, files in os.walk(path):
        for fn in files:
            p = os.path.join(dirpath, fn)
            with open(p, "rb") as f:
                out[os.path.relpath(p, path)] = f.read()
    return out


def test_async_pipeline_byte_identical_to_sync(tmp_path, monkeypatch):
    """2000 rows at 250-row chunks = 8 chunks through every streaming
    stage. A full init/stats/norm/train/eval flow with the background
    pipeline on must produce byte-identical artifacts to the
    WORKERS=0 sequential flow on identically-seeded data."""
    from shifu_tpu.config.path_finder import PathFinder  # noqa: F401
    from shifu_tpu.processor.base import ProcessorContext

    for var in ("SHIFU_TPU_STATS_CHUNK_ROWS", "SHIFU_TPU_NORM_CHUNK_ROWS",
                "SHIFU_TPU_EVAL_CHUNK_ROWS",
                "SHIFU_TPU_ANALYSIS_CHUNK_ROWS"):
        monkeypatch.setenv(var, "250")

    root_sync = _build_root(tmp_path, "sync", seed=20260806)
    root_async = _build_root(tmp_path, "async", seed=20260806)

    monkeypatch.setenv("SHIFU_TPU_PREFETCH_WORKERS", "0")
    _run_flow(root_sync)

    monkeypatch.setenv("SHIFU_TPU_PREFETCH_WORKERS", "2")
    monkeypatch.setenv("SHIFU_TPU_PREFETCH_DEPTH", "2")
    _run_flow(root_async)

    ctx_s = ProcessorContext.load(root_sync)
    ctx_a = ProcessorContext.load(root_async)

    # stats + binning → ColumnConfig bytes
    with open(os.path.join(root_sync, "ColumnConfig.json"), "rb") as f:
        cc_s = f.read()
    with open(os.path.join(root_async, "ColumnConfig.json"), "rb") as f:
        cc_a = f.read()
    assert cc_s == cc_a

    # normalized on-disk layout (dense.npy & friends) byte for byte
    norm_s = _dir_file_bytes(ctx_s.path_finder.normalized_data_path())
    norm_a = _dir_file_bytes(ctx_a.path_finder.normalized_data_path())
    assert sorted(norm_s) == sorted(norm_a)
    for rel in norm_s:
        assert norm_s[rel] == norm_a[rel], f"norm artifact differs: {rel}"

    # streaming trainer → identical parameters (npz containers embed
    # archive metadata, so compare the arrays, not the zip bytes)
    from shifu_tpu.models.spec import load_model
    kind_s, meta_s, p_s = load_model(ctx_s.path_finder.model_path(0, "nn"))
    kind_a, meta_a, p_a = load_model(ctx_a.path_finder.model_path(0, "nn"))
    assert (kind_s, meta_s) == (kind_a, meta_a)
    import jax
    leaves_s = jax.tree_util.tree_leaves(p_s)
    leaves_a = jax.tree_util.tree_leaves(p_a)
    assert len(leaves_s) == len(leaves_a)
    for ls, la in zip(leaves_s, leaves_a):
        np.testing.assert_array_equal(np.asarray(ls), np.asarray(la))

    # streaming eval → EvalScore.csv bytes
    with open(ctx_s.path_finder.eval_score_path("Eval1"), "rb") as f:
        es_s = f.read()
    with open(ctx_a.path_finder.eval_score_path("Eval1"), "rb") as f:
        es_a = f.read()
    assert es_s == es_a

    # observability: the async run's steps.jsonl carries inputPipeline
    # stage timers, and total stall sits strictly below total host
    # parse+assembly time (the overlap actually bought something)
    steps_path = os.path.join(root_async, "tmp", "metrics", "steps.jsonl")
    with open(steps_path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    staged = [r["inputPipeline"] for r in recs if "inputPipeline" in r]
    assert staged, "async run must report pipeline stage timers"
    total_stall = sum(s.get("input_stall_s", 0.0) for s in staged)
    total_parse = sum(s.get("host_parse_s", 0.0)
                      + s.get("host_assemble_s", 0.0) for s in staged)
    assert total_parse > 0
    assert total_stall < total_parse
    assert sum(s.get("chunks", 0) for s in staged) >= 8


# ---------------------------------------------------------------------------
# satellites: retry counters, remote atomic_write, RESUME manifests
# ---------------------------------------------------------------------------

def test_retry_stats_record_site_attempts_and_error(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_FAULT", "unit.flaky:oserror:1-2")
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.001")
    resilience.reset_faults()
    resilience.reset_retry_stats()
    assert resilience.retrying("unit.flaky", lambda: "ok") == "ok"
    stats = resilience.retry_stats()
    assert stats["unit.flaky"]["attempts"] == 2
    assert "OSError" in stats["unit.flaky"]["lastError"]
    # reset=True drains (what step_metrics does per record)
    assert resilience.retry_stats(reset=True)["unit.flaky"]["attempts"] == 2
    assert resilience.retry_stats() == {}


def test_shifu_test_reports_retry_counters(model_set, monkeypatch, caplog):
    from shifu_tpu.cli import main as cli_main
    assert cli_main(["--dir", model_set, "init"]) == 0
    monkeypatch.setenv("SHIFU_TPU_FAULT", "fs.exists:oserror:1")
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.001")
    resilience.reset_faults()
    resilience.reset_retry_stats()
    with caplog.at_level(logging.INFO, logger="shifu_tpu"):
        assert cli_main(["--dir", model_set, "test"]) == 0
    msgs = [r.getMessage() for r in caplog.records]
    assert any("resilience:" in m and ("retried" in m or "no I/O" in m)
               for m in msgs)


def test_remote_atomic_write_commit_and_abort():
    fsspec = pytest.importorskip("fsspec")
    fs = fsspec.filesystem("memory")
    base = "memory://pipe-aw-test"
    if fs.exists("/pipe-aw-test"):
        fs.rm("/pipe-aw-test", recursive=True)

    with resilience.atomic_write(f"{base}/out.txt", "w") as f:
        f.write("hello")
    assert fs.cat("/pipe-aw-test/out.txt") == b"hello"

    with pytest.raises(RuntimeError, match="boom"):
        with resilience.atomic_write(f"{base}/fail.txt", "w") as f:
            f.write("partial")
            raise RuntimeError("boom")
    assert not fs.exists("/pipe-aw-test/fail.txt")
    # no dot-prefixed temp keys linger after commit or abort
    leftovers = [p for p in fs.ls("/pipe-aw-test")
                 if os.path.basename(str(p)).startswith(".")]
    assert leftovers == []


def test_remote_atomic_write_injected_commit_fault(monkeypatch):
    fsspec = pytest.importorskip("fsspec")
    fs = fsspec.filesystem("memory")
    if fs.exists("/pipe-aw-fault"):
        fs.rm("/pipe-aw-fault", recursive=True)
    monkeypatch.setenv("SHIFU_TPU_FAULT", "atomic.commit:oserror:1")
    resilience.reset_faults()
    with pytest.raises(OSError):
        with resilience.atomic_write("memory://pipe-aw-fault/x.txt",
                                     "w") as f:
            f.write("data")
    assert not fs.exists("/pipe-aw-fault/x.txt")


def test_resume_manifests_varselect_train_export(tmp_path, rng,
                                                 monkeypatch, caplog):
    from shifu_tpu.cli import main as cli_main
    from tests.synth import make_model_set

    root = make_model_set(tmp_path, rng, n_rows=600)
    mc_path = os.path.join(root, "ModelConfig.json")
    with open(mc_path) as f:
        mc = json.load(f)
    mc["train"]["numTrainEpochs"] = 4
    with open(mc_path, "w") as f:
        json.dump(mc, f, indent=2)

    for cmd in (["init"], ["stats"], ["varsel"], ["norm"], ["train"],
                ["export", "-t", "columnstats"]):
        assert cli_main(["--dir", root] + cmd) == 0

    for step in ("varselect", "train", "export.columnstats"):
        man = os.path.join(root, "tmp", "manifests", f"{step}.json")
        assert os.path.exists(man), f"{step} must leave a manifest"

    monkeypatch.setenv("SHIFU_TPU_RESUME", "1")
    from shifu_tpu.processor.base import ProcessorContext
    ctx = ProcessorContext.load(root)
    model_file = ctx.path_finder.model_path(0, "nn")
    mtime_before = os.path.getmtime(model_file)

    caplog.clear()
    with caplog.at_level(logging.INFO, logger="shifu_tpu"):
        assert cli_main(["--dir", root, "varsel"]) == 0
        assert cli_main(["--dir", root, "train"]) == 0
        assert cli_main(["--dir", root, "export", "-t",
                         "columnstats"]) == 0
    skip_msgs = [r.getMessage() for r in caplog.records
                 if "skipping" in r.getMessage()]
    assert len(skip_msgs) >= 3, f"expected 3 skips, got: {skip_msgs}"
    # the skipped train really did not rewrite the model
    assert os.path.getmtime(model_file) == mtime_before

    # varselect -reset is an explicit user edit: never skipped
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="shifu_tpu"):
        assert cli_main(["--dir", root, "varsel", "-reset"]) == 0
    assert not any("skipping" in r.getMessage() for r in caplog.records)
