"""Misconfiguration battery: every bad config fails at probe time with
a clean, step-specific message — never as a shape error inside a jitted
kernel (reference: `core/validator/ModelInspector.java:92+` +
`container/meta/*` meta-spec validation)."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.config.inspector import ModelStep, probe
from shifu_tpu.config.model_config import ModelConfig


@pytest.fixture()
def ms(tmp_path, rng):
    from tests.synth import make_model_set
    return make_model_set(tmp_path, rng, n_rows=200)


def _mc(root, **edits):
    """Load the model set's config and apply {'section.key': value}."""
    path = os.path.join(root, "ModelConfig.json")
    raw = json.load(open(path))
    for dotted, v in edits.items():
        cur = raw
        parts = dotted.split(".")
        for p in parts[:-1]:
            cur = cur[p]
        cur[parts[-1]] = v
    json.dump(raw, open(path, "w"))
    return ModelConfig.load(root)


def _causes(mc, step):
    r = probe(mc, step)
    return "" if r.status else "; ".join(r.causes)


# ---- meta-spec range/enum checks ------------------------------------------

def test_empty_name_fails(ms):
    assert "basic#name" in _causes(_mc(ms, **{"basic.name": ""}),
                                   ModelStep.INIT)


def test_bad_max_num_bin(ms):
    assert "maxNumBin" in _causes(_mc(ms, **{"stats.maxNumBin": 1}),
                                  ModelStep.STATS)


def test_huge_max_num_bin(ms):
    assert "maxNumBin" in _causes(_mc(ms, **{"stats.maxNumBin": 99999}),
                                  ModelStep.STATS)


def test_bad_sample_rate(ms):
    assert "sampleRate" in _causes(_mc(ms, **{"stats.sampleRate": 0.0}),
                                   ModelStep.STATS)


def test_bad_std_dev_cutoff(ms):
    assert "stdDevCutOff" in _causes(
        _mc(ms, **{"normalize.stdDevCutOff": -1.0}), ModelStep.NORMALIZE)


def test_bad_precision_type(ms):
    assert "precisionType" in _causes(
        _mc(ms, **{"normalize.precisionType": "FLOAT99"}),
        ModelStep.NORMALIZE)


def test_bad_bagging_num(ms):
    assert "baggingNum" in _causes(_mc(ms, **{"train.baggingNum": 0}),
                                   ModelStep.TRAIN)


def test_bad_valid_set_rate(ms):
    assert "validSetRate" in _causes(
        _mc(ms, **{"train.validSetRate": 1.5}), ModelStep.TRAIN)


def test_bad_epochs(ms):
    assert "numTrainEpochs" in _causes(
        _mc(ms, **{"train.numTrainEpochs": 0}), ModelStep.TRAIN)


def test_bad_upsample_weight(ms):
    assert "upSampleWeight" in _causes(
        _mc(ms, **{"train.upSampleWeight": 0.5}), ModelStep.TRAIN)


def test_bad_learning_rate_param(ms):
    mc = _mc(ms)
    mc.train.params["LearningRate"] = -0.1
    assert "LearningRate" in _causes(mc, ModelStep.TRAIN)


def test_bad_grid_learning_rate_element(ms):
    mc = _mc(ms)
    mc.train.params["LearningRate"] = [0.1, -0.5]
    assert "LearningRate" in _causes(mc, ModelStep.TRAIN)


def test_bad_max_depth_param(ms):
    mc = _mc(ms)
    mc.train.params["MaxDepth"] = 99
    assert "MaxDepth" in _causes(mc, ModelStep.TRAIN)


# ---- semantic / cross-field checks ----------------------------------------

def test_missing_data_path(ms):
    assert "dataPath" in _causes(_mc(ms, **{"dataSet.dataPath": ""}),
                                 ModelStep.INIT)


def test_nonexistent_data_path(ms):
    c = _causes(_mc(ms, **{"dataSet.dataPath": "no/such/file.psv"}),
                ModelStep.INIT)
    assert "does not exist" in c


def test_weight_equals_target(ms):
    mc = _mc(ms)
    mc.dataSet.weightColumnName = mc.dataSet.targetColumnName
    assert "weight column cannot be the target" in _causes(
        mc, ModelStep.INIT)


def test_overlapping_tags(ms):
    mc = _mc(ms)
    mc.dataSet.negTags = list(mc.dataSet.posTags)
    assert "overlap" in _causes(mc, ModelStep.INIT)


def test_empty_pos_tags(ms):
    assert "posTags" in _causes(_mc(ms, **{"dataSet.posTags": []}),
                                ModelStep.INIT)


def test_unknown_activation(ms):
    mc = _mc(ms)
    mc.train.params["NumHiddenLayers"] = 1
    mc.train.params["NumHiddenNodes"] = [8]
    mc.train.params["ActivationFunc"] = ["warpdrive"]
    assert "warpdrive" in _causes(mc, ModelStep.TRAIN)


def test_unknown_propagation(ms):
    mc = _mc(ms)
    mc.train.params["Propagation"] = "WARP"
    assert "Propagation" in _causes(mc, ModelStep.TRAIN)


def test_hidden_layer_mismatch(ms):
    mc = _mc(ms)
    mc.train.params["NumHiddenLayers"] = 2
    mc.train.params["NumHiddenNodes"] = [8]
    mc.train.params["ActivationFunc"] = ["tanh", "tanh"]
    assert "NumHiddenNodes" in _causes(mc, ModelStep.TRAIN)


def test_bad_tree_loss(ms):
    mc = _mc(ms, **{"train.algorithm": "GBT"})
    mc.train.params["Loss"] = "hinge9"
    assert "Loss" in _causes(mc, ModelStep.TRAIN)


def test_bad_subset_strategy(ms):
    mc = _mc(ms, **{"train.algorithm": "RF"})
    mc.train.params["FeatureSubsetStrategy"] = "MOST"
    assert "FeatureSubsetStrategy" in _causes(mc, ModelStep.TRAIN)


def test_fixed_layers_without_continuous(ms):
    mc = _mc(ms)
    mc.train.params["FixedLayers"] = [1]
    assert "isContinuous" in _causes(mc, ModelStep.TRAIN)


def test_fixed_layers_zero_based_rejected(ms):
    """FixedLayers is 1-based like the reference (layer 1 = the
    input→hidden1 weights); 0 is a config error, not a silent no-op."""
    mc = _mc(ms, **{"train.isContinuous": True})
    mc.train.params["FixedLayers"] = [0]
    assert "1-based" in _causes(mc, ModelStep.TRAIN)


def test_fixed_layers_beyond_hidden_rejected(ms):
    mc = _mc(ms, **{"train.isContinuous": True})
    mc.train.params["NumHiddenLayers"] = 2
    mc.train.params["FixedLayers"] = [3]
    assert "NumHiddenLayers" in _causes(mc, ModelStep.TRAIN)


def test_kfold_with_continuous(ms):
    mc = _mc(ms, **{"train.numKFold": 5, "train.isContinuous": True})
    assert "k-fold" in _causes(mc, ModelStep.TRAIN)


def test_grid_search_with_continuous(ms):
    mc = _mc(ms, **{"train.isContinuous": True})
    mc.train.params["LearningRate"] = [0.1, 0.2]
    assert "grid search" in _causes(mc, ModelStep.TRAIN)


def test_missing_grid_config_file(ms):
    mc = _mc(ms, **{"train.gridConfigFile": "grid/nope.txt"})
    assert "gridConfigFile" in _causes(mc, ModelStep.TRAIN)


def test_wdl_requires_index_norm(ms):
    mc = _mc(ms, **{"train.algorithm": "WDL",
                    "normalize.normType": "ZSCALE"})
    assert "INDEX" in _causes(mc, ModelStep.TRAIN)


def test_eval_duplicate_names(ms):
    mc = _mc(ms)
    mc.evals.append(mc.evals[0])
    assert "duplicate" in _causes(mc, ModelStep.EVAL)


def test_eval_bad_bucket_num(ms):
    mc = _mc(ms)
    mc.evals[0].performanceBucketNum = 1
    assert "performanceBucketNum" in _causes(mc, ModelStep.EVAL)


def test_eval_bad_selector(ms):
    mc = _mc(ms)
    mc.evals[0].performanceScoreSelector = "loudest"
    assert "performanceScoreSelector" in _causes(mc, ModelStep.EVAL)


def test_eval_bad_gbt_convert(ms):
    mc = _mc(ms)
    mc.evals[0].gbtScoreConvertStrategy = "SQUARE"
    assert "gbtScoreConvertStrategy" in _causes(mc, ModelStep.EVAL)


def test_eval_missing_data_path(ms):
    mc = _mc(ms)
    mc.evals[0].dataSet.dataPath = ""
    assert "dataPath" in _causes(mc, ModelStep.EVAL)


# ---- typo warnings ---------------------------------------------------------

def test_unknown_key_suggestion(ms):
    path = os.path.join(ms, "ModelConfig.json")
    raw = json.load(open(path))
    raw["train"]["baggingNums"] = 3        # typo of baggingNum
    json.dump(raw, open(path, "w"))
    mc = ModelConfig.load(ms)
    r = probe(mc, ModelStep.TRAIN)
    assert r.status  # warning, not failure (keys are preserved)
    assert any("baggingNums" in w and "baggingNum" in w
               for w in r.warnings)


def test_probe_fails_before_kernel(ms):
    """End-to-end: the processor raises the probe message, not a shape
    error from inside a kernel."""
    from shifu_tpu.processor import init as init_proc
    from shifu_tpu.processor.base import ProcessorContext
    _mc(ms, **{"dataSet.dataPath": "no/such/file.psv"})
    ctx = ProcessorContext.load(ms)
    with pytest.raises(ValueError, match="does not exist"):
        ctx.validate(ModelStep.INIT)


def test_all_tags_invalid_fails_cleanly(ms):
    """Data-dependent check: a target column whose values never match
    posTags/negTags fails with the observed values, not a kernel shape
    error (VERDICT Weak #7 tag-cardinality example)."""
    from shifu_tpu.processor import init as init_proc, stats as stats_proc
    from shifu_tpu.processor.base import ProcessorContext
    _mc(ms, **{"dataSet.posTags": ["yes"], "dataSet.negTags": ["no"]})
    ctx = ProcessorContext.load(ms)
    assert init_proc.run(ctx) == 0
    ctx = ProcessorContext.load(ms)
    with pytest.raises(ValueError, match="posTags"):
        stats_proc.run(ctx)


# ---- round-3 widened meta validation (VERDICT r2 #10) ----------------------

def test_num_kfold_too_large(ms):
    assert "numKFold" in _causes(_mc(ms, **{"train.numKFold": 21}),
                                 ModelStep.TRAIN)


def test_num_kfold_below_disabled_sentinel(ms):
    assert "numKFold" in _causes(_mc(ms, **{"train.numKFold": -2}),
                                 ModelStep.TRAIN)


def test_num_kfold_with_continuous(ms):
    assert "isContinuous" in _causes(
        _mc(ms, **{"train.numKFold": 5, "train.isContinuous": True}),
        ModelStep.TRAIN)


def test_bad_filter_by(ms):
    assert "filterBy" in _causes(_mc(ms, **{"varSelect.filterBy": "BOGUS"}),
                                 ModelStep.VARSELECT)


def test_fss_grid_list_element_checked(ms):
    """A grid-search list for FeatureSubsetStrategy is validated
    element-wise (round-2 gap: lists skipped the check entirely)."""
    mc = _mc(ms, **{"train.algorithm": "GBT",
                    "train.params": {"FeatureSubsetStrategy":
                                     ["ALL", "NOPE", "SQRT"]}})
    assert "NOPE" in _causes(mc, ModelStep.TRAIN)


def test_fss_grid_list_valid_passes(ms):
    mc = _mc(ms, **{"train.algorithm": "GBT",
                    "train.params": {"FeatureSubsetStrategy":
                                     ["ALL", "SQRT", "0.5"]}})
    # "0.5" is not an int nor a named strategy
    assert "0.5" in _causes(mc, ModelStep.TRAIN)


def test_wdl_embed_size_zero(ms):
    mc = _mc(ms, **{"train.algorithm": "WDL",
                    "normalize.normType": "ZSCALE_INDEX",
                    "train.params": {"EmbedSize": 0}})
    assert "EmbedSize" in _causes(mc, ModelStep.TRAIN)


def test_wdl_both_branches_disabled(ms):
    mc = _mc(ms, **{"train.algorithm": "WDL",
                    "normalize.normType": "ZSCALE_INDEX",
                    "train.params": {"WideEnable": False,
                                     "DeepEnable": False}})
    assert "branches" in _causes(mc, ModelStep.TRAIN)


def test_wdl_bad_activation(ms):
    mc = _mc(ms, **{"train.algorithm": "WDL",
                    "normalize.normType": "ZSCALE_INDEX",
                    "train.params": {"ActivationFunc": ["blorp"]}})
    assert "blorp" in _causes(mc, ModelStep.TRAIN)


def test_mtl_bad_hidden_nodes(ms):
    mc = _mc(ms, **{"train.algorithm": "MTL",
                    "train.params": {"NumHiddenNodes": [64, -3]}})
    assert "NumHiddenNodes" in _causes(mc, ModelStep.TRAIN)


def test_regularized_constant_negative(ms):
    mc = _mc(ms, **{"train.params": {"RegularizedConstant": -0.1}})
    assert "RegularizedConstant" in _causes(mc, ModelStep.TRAIN)


def test_tree_param_grid_list_checked(ms):
    """Grid lists for tree params check element-wise (MaxDepth 0)."""
    mc = _mc(ms, **{"train.algorithm": "GBT",
                    "train.params": {"MaxDepth": [6, 0]}})
    assert "MaxDepth" in _causes(mc, ModelStep.TRAIN)


def test_eval_score_meta_file_missing(ms):
    path = os.path.join(ms, "ModelConfig.json")
    raw = json.load(open(path))
    raw["evals"][0]["scoreMetaColumnNameFile"] = "no/such/meta.names"
    json.dump(raw, open(path, "w"))
    mc = ModelConfig.load(ms)
    assert "scoreMetaColumnNameFile" in _causes(mc, ModelStep.EVAL)


def test_eval_tag_overlap(ms):
    path = os.path.join(ms, "ModelConfig.json")
    raw = json.load(open(path))
    raw["evals"][0]["dataSet"]["posTags"] = ["1", "both"]
    raw["evals"][0]["dataSet"]["negTags"] = ["0", "both"]
    json.dump(raw, open(path, "w"))
    mc = ModelConfig.load(ms)
    assert "overlap" in _causes(mc, ModelStep.EVAL)


def test_eval_bucket_num_too_small(ms):
    path = os.path.join(ms, "ModelConfig.json")
    raw = json.load(open(path))
    raw["evals"][0]["performanceBucketNum"] = 1
    json.dump(raw, open(path, "w"))
    mc = ModelConfig.load(ms)
    assert "performanceBucketNum" in _causes(mc, ModelStep.EVAL)


def test_kfold_with_train_on_disk_rejected(ms):
    assert "trainOnDisk" in _causes(
        _mc(ms, **{"train.numKFold": 5, "train.trainOnDisk": True}),
        ModelStep.TRAIN)
