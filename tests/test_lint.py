"""Static-analysis gate + rule unit tests (tier-1).

Two layers:

1. ``test_package_is_clean`` — the acceptance check from ISSUE 4
   (extended by ISSUE 19): the analyzer over the whole package (plus
   bench.py/tools, the out-of-package knob readers) reports ZERO
   findings across all sixteen rules — including the whole-program
   concurrency/atomicity four — within a documented inline-suppression
   budget where every entry carries a ``-- reason``.
2. Per-rule fixtures — positive (a known violation is flagged),
   negative (the clean twin is not), suppressed (the violation with an
   inline ``# lint: disable=`` is silenced but counted) — plus unit
   tests for the runtime lock-order detector (including the deliberate
   A->B / B->A inversion that MUST raise), the whole-program
   call-graph model, and a cross-module thread-mutation fixture a
   per-file engine provably cannot catch.
"""

import ast
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from shifu_tpu.analysis import engine, lockcheck
from shifu_tpu.analysis.lockcheck import CheckedLock, LockOrderError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def lint_source(tmp_path, source, name="fixture.py", rules=None):
    """Run the engine on one fixture snippet; return the Report."""
    path = tmp_path / name
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    return engine.run([str(path)], rules=rules)


def rule_names(report):
    return [f.rule for f in report.findings]


# ---------------------------------------------------------------------------
# the acceptance gate
# ---------------------------------------------------------------------------

def test_package_is_clean():
    report = engine.run([os.path.join(REPO, "shifu_tpu"),
                         os.path.join(REPO, "bench.py"),
                         os.path.join(REPO, "tools"),
                         os.path.join(REPO, "tests", "synth.py")])
    msgs = "\n".join(f.format() for f in report.findings)
    assert not report.findings, f"lint findings:\n{msgs}"
    assert report.files > 60, "walker found suspiciously few files"
    # Suppression budget (every entry carries a `-- reason` inline):
    #   5 non-atomic-write        2 live-tailed subprocess/node logs,
    #                             the drilled ckpt tmp+rename publish
    #                             seam, 2 dot-prefixed eval scratch
    #                             sidecars
    #   3 thread-shared-mutation  resilience._rules_cache idempotent
    #                             memo (deliberately lock-free), 2
    #                             consumer-thread-confined batcher
    #                             carry-overs
    #   2 jit-in-loop             aot warm/compile loops (cached jits)
    #   2 host-sync-in-hot-loop   bench/profiler intentional syncs
    assert len(report.suppressed) <= 12, (
        "suppression budget exceeded — justify or fix: "
        + "\n".join(f.format() for f in report.suppressed))


def test_module_entrypoint_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    bad = tmp_path / "bad.py"
    bad.write_text("import os\n"
                   "x = os.environ.get('SHIFU_TPU_NOT_A_KNOB')\n",
                   encoding="utf-8")
    r = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.analysis", str(bad)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "undeclared-knob" in r.stdout
    r = subprocess.run(
        [sys.executable, "-m", "shifu_tpu.analysis", "--knobs-md"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert r.returncode == 0
    assert "SHIFU_TPU_LOCKCHECK" in r.stdout


# ---------------------------------------------------------------------------
# host-sync-in-hot-loop
# ---------------------------------------------------------------------------

HOT_SYNC_POSITIVE = """
    import jax.numpy as jnp
    import numpy as np

    def run(xs):
        total = 0.0
        for x in xs:
            y = jnp.sum(x)
            total += float(y)
        return total
"""

HOT_SYNC_NEGATIVE = """
    import jax.numpy as jnp
    import numpy as np
    from shifu_tpu.data.pipeline import host_fetch

    def run(xs):
        parts = []
        for x in xs:
            parts.append(jnp.sum(x))       # stays on device
            z = np.asarray(np.ones(3))     # numpy-only: no sync
        return float(host_fetch(jnp.stack(parts)).sum())
"""


def test_host_sync_positive(tmp_path):
    report = lint_source(tmp_path, HOT_SYNC_POSITIVE)
    assert "host-sync-in-hot-loop" in rule_names(report)


def test_host_sync_negative(tmp_path):
    report = lint_source(tmp_path, HOT_SYNC_NEGATIVE)
    assert "host-sync-in-hot-loop" not in rule_names(report)


def test_host_sync_suppressed(tmp_path):
    src = HOT_SYNC_POSITIVE.replace(
        "total += float(y)",
        "total += float(y)  # lint: disable=host-sync-in-hot-loop -- why")
    report = lint_source(tmp_path, src)
    assert "host-sync-in-hot-loop" not in rule_names(report)
    assert any(f.rule == "host-sync-in-hot-loop"
               for f in report.suppressed)


def test_host_sync_item_and_asarray(tmp_path):
    src = """
        import jax.numpy as jnp
        import numpy as np

        def run(xs):
            out = []
            while xs:
                v = jnp.dot(xs.pop(), xs.pop())
                out.append(np.asarray(v))
                s = v.item()
            return out, s
    """
    report = lint_source(tmp_path, src)
    assert rule_names(report).count("host-sync-in-hot-loop") == 2


def test_host_sync_sees_through_local_device_fn(tmp_path):
    # the streaming.py shape: a closure whose return value is the
    # product of a jax.jit-compiled callable
    src = """
        import jax
        import numpy as np

        _jits = {}

        def run(chunks, step):
            def update(s, c):
                f = _jits.get("k")
                if f is None:
                    f = jax.jit(step)
                    _jits["k"] = f
                return f(s, c)

            s, acc = None, 0.0
            for c in chunks:
                s, loss = update(s, c)
                acc += float(loss)
            return s, acc
    """
    report = lint_source(tmp_path, src)
    assert "host-sync-in-hot-loop" in rule_names(report)


# ---------------------------------------------------------------------------
# jit-in-loop
# ---------------------------------------------------------------------------

def test_jit_in_loop_positive(tmp_path):
    src = """
        import jax

        def run(xs, f):
            out = []
            for x in xs:
                out.append(jax.jit(f)(x))
            return out
    """
    report = lint_source(tmp_path, src)
    assert "jit-in-loop" in rule_names(report)


def test_jit_in_loop_negative_hoisted_and_vmap(tmp_path):
    src = """
        import jax

        def run(xs, f):
            jf = jax.jit(f)                  # hoisted: fine
            out = []
            for x in xs:
                out.append(jf(x))
                g = jax.vmap(f)(x)           # vmap is a cheap wrapper
            return out, g
    """
    report = lint_source(tmp_path, src)
    assert "jit-in-loop" not in rule_names(report)


def test_jit_in_loop_suppressed(tmp_path):
    src = """
        import jax

        def run(xs, f):
            out = []
            for x in xs:
                out.append(jax.jit(f)(x))  # lint: disable=jit-in-loop
            return out
    """
    report = lint_source(tmp_path, src)
    assert "jit-in-loop" not in rule_names(report)
    assert any(f.rule == "jit-in-loop" for f in report.suppressed)


# ---------------------------------------------------------------------------
# donation-aliasing
# ---------------------------------------------------------------------------

def test_donation_aliasing_positive(tmp_path):
    src = """
        import jax

        def run(step, state, batch):
            f = jax.jit(step, donate_argnums=(0,))
            out = f(state, batch)
            return state.sum(), out   # reads the donated buffer
    """
    report = lint_source(tmp_path, src)
    assert "donation-aliasing" in rule_names(report)


def test_donation_aliasing_negative_rebound(tmp_path):
    src = """
        import jax

        def run(step, state, batch):
            f = jax.jit(step, donate_argnums=(0,))
            state = f(state, batch)   # rebinding kills the old buffer
            return state.sum()
    """
    report = lint_source(tmp_path, src)
    assert "donation-aliasing" not in rule_names(report)


def test_donation_aliasing_suppressed(tmp_path):
    src = """
        import jax

        def run(step, state, batch):
            f = jax.jit(step, donate_argnums=(0,))
            out = f(state, batch)
            return state.sum(), out  # lint: disable=donation-aliasing
    """
    report = lint_source(tmp_path, src)
    assert "donation-aliasing" not in rule_names(report)
    assert any(f.rule == "donation-aliasing" for f in report.suppressed)


# ---------------------------------------------------------------------------
# undeclared-knob
# ---------------------------------------------------------------------------

def test_undeclared_knob_positive(tmp_path):
    src = """
        import os
        x = os.environ.get("SHIFU_TPU_TOTALLY_NEW_KNOB", "1")
        y = os.getenv("SHIFU_TPU_ANOTHER_ONE")
        z = os.environ["SHIFU_TPU_THIRD"]
    """
    report = lint_source(tmp_path, src, rules=["undeclared-knob"])
    undeclared = [f for f in report.findings
                  if "not declared" in f.message]
    assert len(undeclared) == 3


def test_declared_knob_raw_read_flagged(tmp_path):
    src = """
        import os
        x = os.environ.get("SHIFU_TPU_PREFETCH_DEPTH", "2")
    """
    report = lint_source(tmp_path, src, rules=["undeclared-knob"])
    assert any("knob_int" in f.message for f in report.findings)


def test_registry_accessor_read_clean(tmp_path):
    src = """
        from shifu_tpu.config.environment import knob_int
        x = knob_int("SHIFU_TPU_PREFETCH_DEPTH")
    """
    report = lint_source(tmp_path, src, rules=["undeclared-knob"])
    per_file = [f for f in report.findings if "dead registry" not in
                f.message]
    assert not per_file


def test_knob_accessors_round_trip(monkeypatch):
    from shifu_tpu.config import environment as env
    monkeypatch.setenv("SHIFU_TPU_PREFETCH_DEPTH", "5")
    assert env.knob_int("SHIFU_TPU_PREFETCH_DEPTH") == 5
    monkeypatch.setenv("SHIFU_TPU_PREFETCH_DEPTH", "garbage")
    assert env.knob_int("SHIFU_TPU_PREFETCH_DEPTH") == 2  # registry dflt
    monkeypatch.delenv("SHIFU_TPU_PREFETCH_DEPTH")
    assert env.knob_int("SHIFU_TPU_PREFETCH_DEPTH") == 2
    monkeypatch.setenv("SHIFU_TPU_HIST_SUBTRACT", "0")
    assert env.knob_bool("SHIFU_TPU_HIST_SUBTRACT") is False
    monkeypatch.setenv("SHIFU_TPU_HIST_SUBTRACT", "yes")
    assert env.knob_bool("SHIFU_TPU_HIST_SUBTRACT") is True
    with pytest.raises(KeyError):
        env.knob_int("SHIFU_TPU_NOT_DECLARED_ANYWHERE")
    rows = env.knobs_rows()
    names = {r["name"] for r in rows}
    assert "SHIFU_TPU_LOCKCHECK" in names
    assert len(names) >= 35
    md = env.knobs_markdown()
    for n in names:
        assert n in md


def test_every_package_getenv_is_declared():
    """Acceptance: every literal SHIFU_TPU_* string in the package is a
    declared knob (the analyzer enforces read sites; this sweeps ALL
    literals so even exotic read paths can't smuggle one in)."""
    import re
    from shifu_tpu.config.environment import KNOBS
    knob_shape = re.compile(r"^SHIFU_TPU_[A-Z0-9_]+$")
    bad = []
    pkg = os.path.join(REPO, "shifu_tpu")
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            p = os.path.join(root, fn)
            tree = ast.parse(open(p, encoding="utf-8").read())
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        knob_shape.match(node.value):
                    if node.value in KNOBS:
                        continue
                    bad.append(f"{p}:{node.lineno}: {node.value}")
    assert not bad, "undeclared SHIFU_TPU_* literals:\n" + "\n".join(bad)


# ---------------------------------------------------------------------------
# unregistered-fault-site
# ---------------------------------------------------------------------------

def test_fault_site_positive(tmp_path):
    src = """
        from shifu_tpu.resilience import fault_point

        def go():
            fault_point("pipeline.nonexistent_site")
    """
    report = lint_source(tmp_path, src,
                         rules=["unregistered-fault-site"])
    assert any("pipeline.nonexistent_site" in f.message
               for f in report.findings)


def test_fault_site_negative_registered_and_dynamic(tmp_path):
    src = """
        from shifu_tpu.resilience import fault_point

        def go(step):
            fault_point("pipeline.fetch")
            fault_point(f"step.{step}")
    """
    report = lint_source(tmp_path, src,
                         rules=["unregistered-fault-site"])
    per_file = [f for f in report.findings if f.line > 0]
    assert not per_file


def test_fault_site_dynamic_outside_namespace_flagged(tmp_path):
    src = """
        from shifu_tpu.resilience import fault_point

        def go(x):
            fault_point(f"mystery.{x}")
    """
    report = lint_source(tmp_path, src,
                         rules=["unregistered-fault-site"])
    assert any("namespace" in f.message for f in report.findings)


def test_fault_sites_all_referenced_in_package():
    """Reverse direction of the rule at package scope: no stale
    FAULT_SITES rows (the finalize hook reports them)."""
    report = engine.run([os.path.join(REPO, "shifu_tpu")],
                        rules=["unregistered-fault-site"])
    stale = [f for f in report.findings if "never referenced" in
             f.message]
    assert not stale, "\n".join(f.format() for f in stale)


# ---------------------------------------------------------------------------
# unregistered-dag-step
# ---------------------------------------------------------------------------

def test_dag_step_positive(tmp_path):
    src = """
        from shifu_tpu.processor.base import step_guard

        def go(ctx):
            with step_guard(ctx, "mysterystep") as ok:
                pass
    """
    report = lint_source(tmp_path, src,
                         rules=["unregistered-dag-step"])
    assert any("mysterystep" in f.message for f in report.findings)


def test_dag_step_negative_registered_and_family(tmp_path):
    src = """
        from shifu_tpu.processor.base import step_guard

        def go(ctx, name):
            with step_guard(ctx, "train") as ok:
                pass
            with step_guard(ctx, f"eval.{name}") as ok:
                pass
    """
    report = lint_source(tmp_path, src,
                         rules=["unregistered-dag-step"])
    per_file = [f for f in report.findings if f.line > 0]
    assert not per_file


def test_dag_step_dynamic_outside_family_flagged(tmp_path):
    src = """
        from shifu_tpu.processor.base import step_guard

        def go(ctx, x):
            with step_guard(ctx, f"mystery.{x}") as ok:
                pass
    """
    report = lint_source(tmp_path, src,
                         rules=["unregistered-dag-step"])
    assert any("family prefix" in f.message for f in report.findings)


def test_dag_step_dotted_nonfamily_flagged(tmp_path):
    src = """
        from shifu_tpu.processor.base import step_guard

        def go(ctx):
            with step_guard(ctx, "train.fancy") as ok:
                pass
    """
    report = lint_source(tmp_path, src,
                         rules=["unregistered-dag-step"])
    assert any("train.fancy" in f.message for f in report.findings)


def test_dag_registry_all_guarded_in_package():
    """Reverse direction at package scope: every STEP_REGISTRY entry
    with manifest=True has a live step_guard call site (the finalize
    hook reports stale rows)."""
    report = engine.run([os.path.join(REPO, "shifu_tpu")],
                        rules=["unregistered-dag-step"])
    stale = [f for f in report.findings if "stale entry" in f.message]
    assert not stale, "\n".join(f.format() for f in stale)


# ---------------------------------------------------------------------------
# unregistered-span
# ---------------------------------------------------------------------------

def test_span_positive(tmp_path):
    src = """
        from shifu_tpu.obs.trace import span

        def go():
            with span("mystery.stage"):
                pass
    """
    report = lint_source(tmp_path, src, rules=["unregistered-span"])
    assert any("mystery.stage" in f.message for f in report.findings)


def test_span_negative_registered_and_dynamic(tmp_path):
    src = """
        from shifu_tpu.obs import trace as obs_trace

        def go(node, t0, t1):
            with obs_trace.span("dag.node", node=node):
                pass
            obs_trace.record_span(f"serve.{node}", t0, t1)
    """
    report = lint_source(tmp_path, src, rules=["unregistered-span"])
    per_file = [f for f in report.findings if f.line > 0]
    assert not per_file


def test_span_dynamic_outside_family_flagged(tmp_path):
    src = """
        from shifu_tpu.obs.trace import record_span

        def go(x, t0, t1):
            record_span(f"mystery.{x}", t0, t1)
    """
    report = lint_source(tmp_path, src, rules=["unregistered-span"])
    assert any("prefix" in f.message for f in report.findings)


def test_span_numeric_local_named_span_clean(tmp_path):
    # the stats kernels use `span` as a numeric local (bin widths);
    # only calls whose first argument is a string literal are span
    # emissions
    src = """
        import numpy as np

        def go(hi, lo, span):
            width = np.maximum(hi - lo, 1e-9)
            return span(width)
    """
    report = lint_source(tmp_path, src, rules=["unregistered-span"])
    assert not report.findings


def test_span_suppressed(tmp_path):
    src = """
        from shifu_tpu.obs.trace import span

        def go():
            with span("mystery.stage"):  # lint: disable=unregistered-span -- fixture
                pass
    """
    report = lint_source(tmp_path, src, rules=["unregistered-span"])
    assert not report.findings
    assert any(f.rule == "unregistered-span" for f in report.suppressed)


def test_span_registry_all_emitted_in_package():
    """Reverse direction at package scope: every SPAN_FAMILIES entry
    has a live span()/record_span() call site (the finalize hook
    reports dead vocabulary rows)."""
    report = engine.run([os.path.join(REPO, "shifu_tpu")],
                        rules=["unregistered-span"])
    dead = [f for f in report.findings if "never emitted" in f.message]
    assert not dead, "\n".join(f.format() for f in dead)


# ---------------------------------------------------------------------------
# unwatched-collective
# ---------------------------------------------------------------------------

def test_unwatched_collective_positive(tmp_path):
    src = """
        from jax.experimental import multihost_utils
        import jax

        def merge(tree):
            return multihost_utils.process_allgather(tree)

        def assemble(mesh, spec, arrs):
            return jax.make_array_from_process_local_data(spec, arrs)

        def reduce_host(x):
            return jax.lax.psum(x, "data")
    """
    report = lint_source(tmp_path, src,
                         rules=["unwatched-collective"])
    assert len(report.findings) == 3, rule_names(report)
    assert all("watched dist wrapper" in f.message
               for f in report.findings)


def test_unwatched_collective_negative_compiled_and_wrapped(tmp_path):
    src = """
        import functools
        import jax
        from jax.experimental.shard_map import shard_map

        from shifu_tpu.parallel import dist

        @jax.jit
        def device_sum(x):
            return jax.lax.psum(x, "data")

        @functools.partial(shard_map, mesh=None,
                           in_specs=None, out_specs=None)
        def mapped(x):
            return jax.lax.pmean(x, "data")

        def merge(tree):
            return dist.allreduce_tree("fixture.merge", tree)
    """
    report = lint_source(tmp_path, src,
                         rules=["unwatched-collective"])
    assert not report.findings, rule_names(report)


def test_unwatched_collective_dist_module_exempt(tmp_path):
    (tmp_path / "shifu_tpu" / "parallel").mkdir(parents=True)
    src = """
        from jax.experimental import multihost_utils

        def _gather(tree):
            return multihost_utils.process_allgather(tree)
    """
    report = lint_source(tmp_path, src,
                         name="shifu_tpu/parallel/dist.py",
                         rules=["unwatched-collective"])
    assert not report.findings, rule_names(report)


def test_unwatched_collective_suppressed(tmp_path):
    src = """
        from jax.experimental import multihost_utils

        def merge(tree):
            return multihost_utils.process_allgather(tree)  # lint: disable=unwatched-collective -- fixture
    """
    report = lint_source(tmp_path, src,
                         rules=["unwatched-collective"])
    assert not report.findings
    assert any(f.rule == "unwatched-collective"
               for f in report.suppressed)


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------

def test_blocking_under_lock_positive(tmp_path):
    src = """
        import threading
        import time

        _lock = threading.Lock()

        def go(work_queue):
            with _lock:
                time.sleep(1.0)
                item = work_queue.get()
            return item
    """
    report = lint_source(tmp_path, src, rules=["blocking-under-lock"])
    assert rule_names(report).count("blocking-under-lock") == 2


def test_blocking_under_lock_negative(tmp_path):
    src = """
        import threading
        import time

        _lock = threading.Lock()

        def go(work_queue, d):
            with _lock:
                v = d.get("key")          # dict.get: not blocking
                snapshot = list(d)
            time.sleep(0.1)               # outside the lock: fine
            item = work_queue.get()       # outside the lock: fine
            return v, snapshot, item
    """
    report = lint_source(tmp_path, src, rules=["blocking-under-lock"])
    assert "blocking-under-lock" not in rule_names(report)


def test_blocking_under_lock_nested_function_exempt(tmp_path):
    src = """
        import threading
        import time

        _lock = threading.Lock()

        def go():
            with _lock:
                def later():
                    time.sleep(5)      # runs after release
                return later
    """
    report = lint_source(tmp_path, src, rules=["blocking-under-lock"])
    assert "blocking-under-lock" not in rule_names(report)


# ---------------------------------------------------------------------------
# runtime lock-order detector
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _fresh_lock_graph():
    lockcheck.reset()
    yield
    lockcheck.reset()


def test_lock_inversion_detected():
    """Deliberate A->B / B->A inversion MUST raise LockOrderError."""
    a, b = CheckedLock("A"), CheckedLock("B")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()

    with pytest.raises(LockOrderError, match="cycle"):
        with b:
            with a:
                pass


def test_consistent_order_passes():
    a, b, c = CheckedLock("A"), CheckedLock("B"), CheckedLock("C")
    errors = []

    def worker():
        try:
            for _ in range(50):
                with a:
                    with b:
                        with c:
                            pass
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_reacquire_same_lock_raises():
    a = CheckedLock("A")
    with a:
        with pytest.raises(LockOrderError, match="re-acquired"):
            a.acquire()


def test_transitive_cycle_detected():
    a, b, c = CheckedLock("A"), CheckedLock("B"), CheckedLock("C")
    for first, second in ((a, b), (b, c)):
        def run(x=first, y=second):
            with x:
                with y:
                    pass
        th = threading.Thread(target=run)
        th.start()
        th.join()
    # A->B and B->C recorded; C->A closes the cycle transitively
    with pytest.raises(LockOrderError, match="cycle"):
        with c:
            with a:
                pass


def test_make_lock_plain_by_default(monkeypatch):
    monkeypatch.delenv("SHIFU_TPU_LOCKCHECK", raising=False)
    lk = lockcheck.make_lock("plain")
    assert not isinstance(lk, CheckedLock)
    monkeypatch.setenv("SHIFU_TPU_LOCKCHECK", "1")
    lk = lockcheck.make_lock("checked")
    assert isinstance(lk, CheckedLock)
    with lk:
        assert lk.locked()
    assert not lk.locked()


def test_runtime_modules_use_the_shim(monkeypatch):
    """resilience/pipeline/dist locks run instrumented under
    SHIFU_TPU_LOCKCHECK=1: exercise the real lock sites in-process and
    assert edges/state stay coherent (no LockOrderError)."""
    monkeypatch.setenv("SHIFU_TPU_LOCKCHECK", "1")
    import importlib
    from shifu_tpu import resilience as res
    from shifu_tpu.data import pipeline as pipe
    from shifu_tpu.parallel import dist
    for mod in (res, pipe, dist):
        importlib.reload(mod)
    try:
        assert isinstance(pipe._timers_lock, CheckedLock)
        assert isinstance(res._retry_lock, CheckedLock)
        assert isinstance(res._events_lock, CheckedLock)
        assert isinstance(dist._inflight_lock, CheckedLock)
        pipe.add_stage_time("host_parse_s", 0.01)
        pipe.drain_stage_timers()
        res.note_event({"kind": "test"})
        res.drain_events()
        assert dist.inflight_collectives() == {}
    finally:
        monkeypatch.delenv("SHIFU_TPU_LOCKCHECK")
        for mod in (res, pipe, dist):
            importlib.reload(mod)


# ---------------------------------------------------------------------------
# unsharded-device-put
# ---------------------------------------------------------------------------

def test_unsharded_device_put_positive(tmp_path):
    src = """
        import jax

        def run(mesh, chunk):
            return jax.device_put(chunk)
    """
    report = lint_source(tmp_path, src, rules=["unsharded-device-put"])
    assert "unsharded-device-put" in rule_names(report)


def test_unsharded_device_put_negative(tmp_path):
    src = """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def run(mesh, chunk, params, shardings):
            a = jax.device_put(chunk, NamedSharding(mesh, P("data")))
            b = jax.device_put(chunk, device=jax.devices()[0])
            # a function REFERENCE is not a call missing its sharding
            c = jax.tree.map(jax.device_put, params, shardings)
            return a, b, c
    """
    report = lint_source(tmp_path, src, rules=["unsharded-device-put"])
    assert "unsharded-device-put" not in rule_names(report)


def test_unsharded_device_put_suppressed(tmp_path):
    src = """
        import jax

        def run(chunk):
            return jax.device_put(chunk)  # lint: disable=unsharded-device-put -- scalar
    """
    report = lint_source(tmp_path, src, rules=["unsharded-device-put"])
    assert "unsharded-device-put" not in rule_names(report)
    assert any(f.rule == "unsharded-device-put" for f in report.suppressed)


# ---------------------------------------------------------------------------
# ungated-device-grab
# ---------------------------------------------------------------------------

def test_ungated_device_grab_positive(tmp_path):
    src = """
        import jax

        def place(x):
            first = jax.devices()[0]
            mine = jax.local_devices()
            return first, mine
    """
    report = lint_source(tmp_path, src, rules=["ungated-device-grab"])
    assert rule_names(report).count("ungated-device-grab") == 2


def test_ungated_device_grab_negative(tmp_path):
    src = """
        import jax
        from shifu_tpu.parallel import mesh as mesh_mod

        def place(x):
            devs = mesh_mod.leased_devices()
            mine = mesh_mod.leased_local_devices()
            n = mesh_mod.device_inventory()
            k = jax.local_device_count()     # a count, not a grab
            ref = jax.devices                # reference, never called
            return devs, mine, n, k, ref
    """
    report = lint_source(tmp_path, src, rules=["ungated-device-grab"])
    assert "ungated-device-grab" not in rule_names(report)


def test_ungated_device_grab_exempts_mesh_module(tmp_path):
    """parallel/mesh.py IS the lease seam — its own jax.devices() calls
    are the one place the whole pool may be read."""
    (tmp_path / "parallel").mkdir()
    src = """
        import jax

        def leased_devices():
            return jax.devices()
    """
    report = lint_source(tmp_path, src, name="parallel/mesh.py",
                         rules=["ungated-device-grab"])
    assert "ungated-device-grab" not in rule_names(report)


def test_ungated_device_grab_suppressed(tmp_path):
    src = """
        import jax

        def probe():
            return jax.devices()  # lint: disable=ungated-device-grab -- diag
    """
    report = lint_source(tmp_path, src, rules=["ungated-device-grab"])
    assert "ungated-device-grab" not in rule_names(report)
    assert any(f.rule == "ungated-device-grab" for f in report.suppressed)


# ---------------------------------------------------------------------------
# lockcheck held-time histograms
# ---------------------------------------------------------------------------

def test_held_time_stats_recorded_per_site():
    lk = CheckedLock("histo")
    for _ in range(5):
        with lk:
            pass
    stats = lockcheck.held_time_stats()
    assert "histo" in stats
    (site, st), = stats["histo"].items()
    assert "test_lint.py:" in site
    assert st["count"] == 5
    assert st["max_s"] >= 0
    assert st["total_s"] >= st["max_s"]
    rep = lockcheck.report()
    assert rep["held"] == stats
    lockcheck.reset()
    assert lockcheck.held_time_stats() == {}


def test_ckpt_writer_lock_holds_are_submillisecond(tmp_path, monkeypatch):
    """ISSUE-5 satellite: the async-checkpoint writer lock guards only
    pointer swaps — instrumented, every hold must be far under a
    millisecond even while real saves run."""
    monkeypatch.setenv("SHIFU_TPU_CKPT_ASYNC", "1")
    import numpy as np
    from shifu_tpu.train import checkpoint as ckpt
    w = ckpt.AsyncCheckpointWriter()
    monkeypatch.setattr(w, "_lock", CheckedLock("ckpt.writer"))
    state = {"w": np.zeros((256, 256), np.float32)}
    for step in range(1, 4):
        w.save(str(tmp_path / "ck"), step, state)
    w.flush()
    stats = lockcheck.held_time_stats()
    assert "ckpt.writer" in stats
    for site, st in stats["ckpt.writer"].items():
        # sub-ms by design; 5ms ceiling absorbs CI scheduler noise
        assert st["max_s"] < 0.005, (site, st)


def test_lockcheck_atexit_dump_lists_graph_and_held(tmp_path):
    """A LOCKCHECK=1 process must end with the lock graph AND the
    held-time histogram on stderr."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SHIFU_TPU_LOCKCHECK="1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    prog = ("from shifu_tpu.analysis.lockcheck import make_lock\n"
            "a = make_lock('outer'); b = make_lock('inner')\n"
            "with a:\n"
            "    with b:\n"
            "        pass\n")
    r = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, env=env, cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "outer -> inner" in r.stderr
    assert "held-time per acquisition site" in r.stderr
    assert "outer @" in r.stderr and "inner @" in r.stderr


# ---------------------------------------------------------------------------
# java-property-key
# ---------------------------------------------------------------------------

def test_javaprop_positive(tmp_path):
    src = """
        def chunk_rows(props):
            return int(props.get("shifu.foo.chunkRows", 0))
    """
    report = lint_source(tmp_path, src, rules=["java-property-key"])
    assert rule_names(report) == ["java-property-key"]
    assert "shifu.foo.chunkRows" in report.findings[0].message


def test_javaprop_negative(tmp_path):
    src = """
        def chunk_rows(props):
            # a declared key is fine anywhere; one-segment dotted
            # strings (module paths, filenames) never match
            a = props.get("shifu.norm.chunkRows")
            b = "shifu.config"
            c = "not.a.shifu.key"
            return a, b, c
    """
    report = lint_source(tmp_path, src, rules=["java-property-key"])
    assert "java-property-key" not in rule_names(report)


def test_javaprop_docstring_mention_clean(tmp_path):
    src = '''
        def helper():
            """Prose mentioning shifu.bogus.key is documentation,
            not a reference."""
            return "shifu.bogus.key"
    '''
    report = lint_source(tmp_path, src, rules=["java-property-key"])
    # the docstring is skipped; the return-value literal IS flagged
    assert len(report.findings) == 1
    assert report.findings[0].line > 4


def test_javaprop_config_dir_exempt(tmp_path):
    cfg = tmp_path / "config"
    cfg.mkdir()
    path = cfg / "props.py"
    path.write_text('KEY = "shifu.anything.goes"\n', encoding="utf-8")
    report = engine.run([str(path)], rules=["java-property-key"])
    assert not report.findings


def test_javaprop_suppressed(tmp_path):
    src = """
        def chunk_rows(props):
            return props.get("shifu.foo.chunkRows")  # lint: disable=java-property-key -- fixture
    """
    report = lint_source(tmp_path, src, rules=["java-property-key"])
    assert not report.findings
    assert any(f.rule == "java-property-key" for f in report.suppressed)


def test_javaprop_registry_entries_all_referenced():
    """The dead-entry sweep over the real package: every JAVA_PROPS key
    has a live read site (subset of test_package_is_clean, kept
    separate so a dead entry names this invariant directly)."""
    report = engine.run([os.path.join(REPO, "shifu_tpu")],
                        rules=["java-property-key"])
    dead = [f for f in report.findings if "dead JAVA_PROPS" in f.message]
    assert not dead, "\n".join(f.format() for f in dead)


# ---------------------------------------------------------------------------
# raw-lock
# ---------------------------------------------------------------------------

def test_raw_lock_positive(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _rlock = threading.RLock()
    """
    report = lint_source(tmp_path, src, rules=["raw-lock"])
    assert rule_names(report).count("raw-lock") == 2
    # the RLock variant must point at make_lock's reentrant spelling
    assert any("reentrant=True" in f.message for f in report.findings)


def test_raw_lock_from_import_positive(tmp_path):
    src = """
        from threading import Lock

        _lock = Lock()
    """
    report = lint_source(tmp_path, src, rules=["raw-lock"])
    assert rule_names(report) == ["raw-lock"]


def test_raw_lock_negative(tmp_path):
    src = """
        import threading

        from shifu_tpu.resilience import make_lock

        _lock = make_lock("fixture.lock")
        _rlock = make_lock("fixture.rlock", reentrant=True)
        _stop = threading.Event()        # not a lock
        _cond = threading.Condition()    # not in ordering scope


        class Lock:                      # local class, not threading's
            pass


        _fake = Lock()
    """
    report = lint_source(tmp_path, src, rules=["raw-lock"])
    assert "raw-lock" not in rule_names(report)


def test_raw_lock_lockcheck_module_exempt(tmp_path):
    (tmp_path / "shifu_tpu" / "analysis").mkdir(parents=True)
    src = """
        import threading

        _graph_lock = threading.Lock()
    """
    report = lint_source(tmp_path, src,
                         name="shifu_tpu/analysis/lockcheck.py",
                         rules=["raw-lock"])
    assert not report.findings


def test_raw_lock_suppressed(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()  # lint: disable=raw-lock -- fixture
    """
    report = lint_source(tmp_path, src, rules=["raw-lock"])
    assert not report.findings
    assert any(f.rule == "raw-lock" for f in report.suppressed)


# ---------------------------------------------------------------------------
# thread-shared-mutation
# ---------------------------------------------------------------------------

THREAD_SHARE_POSITIVE = """
    import threading

    from shifu_tpu.resilience import make_lock


    class Worker:
        def __init__(self):
            self.count = 0           # __init__ writes are exempt
            self.lock = make_lock("fixture.worker")

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            self.count += 1
"""


def test_thread_share_positive_with_witness(tmp_path):
    report = lint_source(tmp_path, THREAD_SHARE_POSITIVE,
                         rules=["thread-shared-mutation"])
    assert rule_names(report) == ["thread-shared-mutation"]
    f = report.findings[0]
    assert "self.count" in f.message
    # the message carries the entry-point witness, not just a claim
    assert "Thread@fixture.py" in f.message and "via" in f.message


def test_thread_share_negative_locked_write(tmp_path):
    src = THREAD_SHARE_POSITIVE.replace(
        "            self.count += 1",
        "            with self.lock:\n"
        "                self.count += 1")
    report = lint_source(tmp_path, src,
                         rules=["thread-shared-mutation"])
    assert "thread-shared-mutation" not in rule_names(report)


def test_thread_share_negative_unreached_writer(tmp_path):
    src = """
        class Plain:
            def bump(self):
                self.n = 1    # no thread entry reaches this
    """
    report = lint_source(tmp_path, src,
                         rules=["thread-shared-mutation"])
    assert not report.findings


def test_thread_share_suppressed(tmp_path):
    src = THREAD_SHARE_POSITIVE.replace(
        "self.count += 1",
        "self.count += 1  # lint: disable=thread-shared-mutation -- fixture")
    report = lint_source(tmp_path, src,
                         rules=["thread-shared-mutation"])
    assert not report.findings
    assert any(f.rule == "thread-shared-mutation"
               for f in report.suppressed)


CROSS_WORKER = """
    counter = 0


    def run_loop():
        global counter
        counter += 1
"""

CROSS_STARTER = """
    import threading

    from xworker import run_loop


    def go():
        t = threading.Thread(target=run_loop, daemon=True)
        t.start()
        return t
"""


def test_thread_share_cross_module_needs_whole_program(tmp_path):
    """The ISSUE-19 acceptance fixture: the thread start lives in one
    module, the unlocked shared write in another. Each file alone is
    provably clean under per-file analysis (no entry / no write); only
    the call-graph pass connects them."""
    w = tmp_path / "xworker.py"
    w.write_text(textwrap.dedent(CROSS_WORKER), encoding="utf-8")
    s = tmp_path / "xstarter.py"
    s.write_text(textwrap.dedent(CROSS_STARTER), encoding="utf-8")
    assert not engine.run([str(w)],
                          rules=["thread-shared-mutation"]).findings
    assert not engine.run([str(s)],
                          rules=["thread-shared-mutation"]).findings
    report = engine.run([str(w), str(s)],
                        rules=["thread-shared-mutation"])
    assert rule_names(report) == ["thread-shared-mutation"]
    f = report.findings[0]
    assert f.path.endswith("xworker.py")
    assert "global counter" in f.message
    assert "Thread@xstarter.py" in f.message


# ---------------------------------------------------------------------------
# non-atomic-write
# ---------------------------------------------------------------------------

def test_non_atomic_write_positive(tmp_path):
    src = """
        import json
        import os


        def save(path, rows, tmp):
            with open(path, "w", encoding="utf-8") as f:
                f.write("hello")
            os.replace(tmp, path)
            os.rename(tmp, path + ".2")
    """
    report = lint_source(tmp_path, src, rules=["non-atomic-write"])
    assert rule_names(report).count("non-atomic-write") == 3


def test_non_atomic_write_negative(tmp_path):
    src = """
        from shifu_tpu.resilience import atomic_path, atomic_write


        def save(path, log_path):
            with atomic_write(path, "w", encoding="utf-8") as f:
                f.write("hello")
            with atomic_path(path) as tmp:
                # staging into the atomic context's temp is the seam
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write("staged")
            with open(log_path, "a", encoding="utf-8") as f:
                f.write("line")        # append: torn tail at worst
            with open(path, encoding="utf-8") as f:
                return f.read()        # reads are never flagged
    """
    report = lint_source(tmp_path, src, rules=["non-atomic-write"])
    assert "non-atomic-write" not in rule_names(report)


def test_non_atomic_write_sanctioned_module_exempt(tmp_path):
    (tmp_path / "shifu_tpu" / "data").mkdir(parents=True)
    src = """
        import os


        def _commit(tmp, path):
            os.replace(tmp, path)    # fs.py IS the atomic seam
    """
    report = lint_source(tmp_path, src,
                         name="shifu_tpu/data/fs.py",
                         rules=["non-atomic-write"])
    assert not report.findings


def test_non_atomic_write_suppressed(tmp_path):
    src = """
        def save(path):
            with open(path, "w") as f:  # lint: disable=non-atomic-write -- fixture
                f.write("x")
    """
    report = lint_source(tmp_path, src, rules=["non-atomic-write"])
    assert not report.findings
    assert any(f.rule == "non-atomic-write" for f in report.suppressed)


# ---------------------------------------------------------------------------
# swallowed-exception
# ---------------------------------------------------------------------------

def test_swallowed_exception_positive(tmp_path):
    src = '''
        def lossy(fn):
            try:
                return fn()
            except Exception:
                pass


        def lossy2(fn):
            try:
                return fn()
            except:
                "docstring-shaped silence"
    '''
    report = lint_source(tmp_path, src, rules=["swallowed-exception"])
    assert rule_names(report).count("swallowed-exception") == 2


def test_swallowed_exception_negative(tmp_path):
    src = """
        import logging
        import queue

        log = logging.getLogger(__name__)


        def ok(fn, q):
            try:
                return fn()
            except ValueError:
                log.warning("fell back")    # log line: evidence
            try:
                return q.get_nowait()
            except queue.Empty:
                pass                        # absence IS the answer
            try:
                return fn()
            except RuntimeError:
                raise                       # re-raise: evidence
            try:
                return fn()
            except OSError:
                fallback = None             # recorded fallback
                return fallback
    """
    report = lint_source(tmp_path, src, rules=["swallowed-exception"])
    assert "swallowed-exception" not in rule_names(report)


def test_swallowed_exception_absorbed_helper_is_evidence(tmp_path):
    src = """
        from shifu_tpu.resilience import absorbed


        def ok(fn):
            try:
                return fn()
            except Exception as e:
                absorbed("fixture.site", e)
    """
    report = lint_source(tmp_path, src, rules=["swallowed-exception"])
    assert not report.findings


def test_swallowed_exception_suppressed(tmp_path):
    src = """
        def lossy(fn):
            try:
                return fn()
            except Exception:  # lint: disable=swallowed-exception -- fixture
                pass
    """
    report = lint_source(tmp_path, src, rules=["swallowed-exception"])
    assert not report.findings
    assert any(f.rule == "swallowed-exception"
               for f in report.suppressed)


def test_absorbed_counter_runtime():
    """The sanctioned-absorb helper leaves the monitoring evidence the
    rule's message promises: a per-site counter snapshot."""
    from shifu_tpu import resilience as res
    before = res.absorb_counts().get("lint.fixture", 0)
    try:
        raise ValueError("boom")
    except ValueError as e:
        res.absorbed("lint.fixture", e)
    assert res.absorb_counts()["lint.fixture"] == before + 1


# ---------------------------------------------------------------------------
# whole-program model (pass 1): call graph, thread entries, lock scopes
# ---------------------------------------------------------------------------

def build_program(tmp_path, files):
    """Assemble a Program from {name: source} the way engine pass 1
    does."""
    from shifu_tpu.analysis import program as program_mod
    parsed = []
    for name, src in files.items():
        p = tmp_path / name
        p.write_text(textwrap.dedent(src), encoding="utf-8")
        parsed.append((str(p),
                       ast.parse(p.read_text(encoding="utf-8"))))
    return program_mod.build(parsed)


def test_program_thread_and_submit_entries(tmp_path):
    prog = build_program(tmp_path, {
        "w.py": """
            def job():
                return 1


            def other():
                return 2
        """,
        "s.py": """
            import threading

            from w import job, other


            def go(pool):
                threading.Thread(target=job, daemon=True).start()
                pool.submit(other)
        """,
    })
    got = {(e.qname, e.via) for e in prog.entries}
    assert ("w.job", "Thread") in got
    assert ("w.other", "submit") in got


def test_program_lock_scope_attribution(tmp_path):
    prog = build_program(tmp_path, {"m.py": """
        class C:
            def bump(self):
                with self._lock:
                    self.a = 1
                self.b = 2
                with self._cond:   # Condition holds its lock too
                    self.c = 3
    """})
    writes = {w.target: w.locked
              for w in prog.functions["m.C.bump"].writes}
    assert writes == {"self.a": True, "self.b": False, "self.c": True}


def test_program_locked_call_edges_gate_reachability(tmp_path):
    prog = build_program(tmp_path, {"m.py": """
        import threading


        class C:
            def start(self):
                threading.Thread(target=self.run).start()

            def run(self):
                with self._lock:
                    self.guarded()
                self.open_call()

            def guarded(self):
                self.x = 1

            def open_call(self):
                self.y = 2
    """})
    reach = prog.reachable_from_threads()
    assert reach["m.C.run"] is True
    # only ever entered through a locked call site: writes inside are
    # attributed to the caller's lock
    assert reach["m.C.guarded"] is False
    assert reach["m.C.open_call"] is True
    witness = prog.thread_witness("m.C.open_call")
    assert witness.startswith("Thread@m.py:")
    assert "C.run" in witness and "C.open_call" in witness


def test_program_unresolvable_call_has_no_edge(tmp_path):
    """Precision bias: a call the resolver cannot place produces no
    edge — never false reachability."""
    prog = build_program(tmp_path, {"m.py": """
        import threading


        def run(cb):
            cb()                  # opaque callable: no edge


        def go():
            threading.Thread(target=run).start()
    """})
    edges = prog.edges()
    assert edges.get("m.run", []) == []
    assert prog.reachable_from_threads() == {"m.run": True}


# ---------------------------------------------------------------------------
# the converted make_lock sites in the LOCKCHECK=1 DAG report
# ---------------------------------------------------------------------------

def test_converted_locks_in_lockcheck_graph(tmp_path):
    """ISSUE-19 acceptance: the five former raw-lock sites
    (service.schema, fleet.arm, fleet.registry, fleet.lat,
    native.init) plus the locks this PR introduced (batcher.stats,
    resilience.absorb) all construct through make_lock, import clean
    under SHIFU_TPU_LOCKCHECK=1, and show up in the DAG report once
    exercised."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", SHIFU_TPU_LOCKCHECK="1",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    prog = textwrap.dedent("""\
        import json, os
        import numpy as np

        # minimal published registry: FleetService reads manifests
        # only; model residency stays lazy
        os.makedirs("reg/models/m1/v001", exist_ok=True)
        with open("reg/models/m1/v001/manifest.json", "w") as f:
            json.dump({"family": "NN"}, f)
        with open("reg/models/m1/HEAD", "w") as f:
            f.write("v001")
        from shifu_tpu.models.spec import save_model
        save_model("model0.npz", "lr", {"n_in": 3},
                   {"w": np.zeros(3, np.float32),
                    "b": np.zeros(1, np.float32)})

        from shifu_tpu.analysis import lockcheck
        from shifu_tpu import native, resilience
        from shifu_tpu.serve import batcher, fleet, service

        with native._lock:
            pass
        resilience.absorbed("lockcheck.fixture", None)
        batcher.MicroBatcher(lambda b: None, max_rows=8).stats()
        arm = fleet._ArmState("m", "v", "d", 0.1, 0.05, 16, 4)
        with arm._lock:
            pass
        fl = fleet.FleetService("reg", hbm_budget_mb=0)
        with fl._lock:
            with fl._lock:      # fleet.registry is reentrant: legal
                pass
        with fl._lat_lock:
            pass
        svc = service.ScorerService(model_paths=["model0.npz"],
                                    aot_compile=False)
        with svc._schema_lock:
            pass
        print("HELD:" + ",".join(sorted(lockcheck.report()["held"])))
    """)
    r = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, env=env, cwd=str(tmp_path), timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    held = set(r.stdout.split("HELD:")[1].strip().split(","))
    assert {"service.schema", "fleet.arm", "fleet.registry",
            "fleet.lat", "native.init", "batcher.stats",
            "resilience.absorb"} <= held, held
