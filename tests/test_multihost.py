"""DCN scale-out proof: two REAL jax processes (gloo CPU collectives
over localhost = the test rig for multi-host DCN), streaming trainer
end-to-end, results matching a single-process run with the same global
device count.

This is the JAX analog of the reference's multi-machine substrate
(Guagua workers each reading their own HDFS split, SURVEY.md §2.9):
here each process serves only its slice of every chunk and
`jax.make_array_from_process_local_data` assembles the global
row-sharded array. VERDICT r2 Missing #2 / Next #4.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run(nproc: int, out: str, local_devices: int, timeout=420,
         mode=None, env_extra=None):
    """Launch `nproc` worker processes and wait; return proc-0 output."""
    port = _free_port()
    env = dict(os.environ)
    # the workers set their own JAX env before importing jax; scrub the
    # parent test session's pinned platform/flags so they don't leak
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if env_extra:
        env.update(env_extra)
    cmd_tail = ["--mode", mode] if mode else []
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--port", str(port),
             "--nproc", str(nproc), "--pid", str(i), "--out", out,
             "--local-devices", str(local_devices)] + cmd_tail,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for i in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            so, se = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, so, se))
    for rc, so, se in outs:
        assert rc == 0, f"worker failed rc={rc}:\n{se[-3000:]}"
    return outs


@pytest.mark.slow
def test_two_process_streaming_matches_single_process(tmp_path):
    """2 procs × 2 local devices vs 1 proc × 4 devices: same global
    mesh size, same chunk schedule, same bag membership (counter-based
    Philox on GLOBAL row index) → same models."""
    out2 = str(tmp_path / "mh2.npz")
    out1 = str(tmp_path / "mh1.npz")
    _run(2, out2, local_devices=2)
    _run(1, out1, local_devices=4)
    a = np.load(out2)
    b = np.load(out1)
    assert int(a["n_global_devices"]) == 4
    assert int(b["n_global_devices"]) == 4
    # identical global math up to f32 reduction-order noise
    np.testing.assert_allclose(a["val_errors"], b["val_errors"],
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(a["train_errors"], b["train_errors"],
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(a["params0"], b["params0"],
                               rtol=5e-3, atol=5e-4)
    # resident-path global device_put executed on both rigs and agreed
    np.testing.assert_allclose(a["row_sum"], b["row_sum"], rtol=1e-5)


def test_two_process_survivor_escapes_dead_peer(tmp_path):
    """Dead-peer drill (no @slow: this is the hang-proofing acceptance
    test). Two processes rendezvous; process 1 SIGKILLs itself; the
    survivor walks into a barrier its peer will never reach. With
    SHIFU_TPU_BARRIER_TIMEOUT_S set it must EXIT — DistTimeout from
    the watchdog (rc 17) or a fast collective error on the dead
    connection (rc 18) — well inside the subprocess timeout, never
    hanging until the test harness kills it."""
    import signal
    import time

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SHIFU_TPU_BARRIER_TIMEOUT_S"] = "6"
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--port", str(port),
             "--nproc", "2", "--pid", str(i),
             "--out", str(tmp_path / "unused.npz"),
             "--local-devices", "1", "--mode", "barrier-kill"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            so, se = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("survivor hung past the barrier timeout "
                        "(watchdog failed)")
        outs.append((p.returncode, so, se))
    elapsed = time.monotonic() - t0
    rc1, _, se1 = outs[1]
    assert rc1 == -signal.SIGKILL, f"victim rc={rc1}:\n{se1[-2000:]}"
    rc0, _, se0 = outs[0]
    assert rc0 in (17, 18), f"survivor rc={rc0}:\n{se0[-3000:]}"
    assert "DIST_TIMEOUT" in se0 or "DIST_FAIL" in se0, se0[-3000:]
    if rc0 == 17:
        # the watchdog path: DistTimeout raised and thread stacks dumped
        assert "thread stacks" in se0, se0[-3000:]
    # generous wall bound: startup + 6s barrier timeout, nowhere near
    # an indefinite hang
    assert elapsed < 150, f"took {elapsed:.0f}s — watchdog too slow"


def test_two_process_survivor_times_out_on_stuck_peer(tmp_path):
    """Stuck-peer drill: the peer stays ALIVE (sockets open, nothing
    errors fast) but never reaches the barrier — the hang class only
    the watchdog can catch. The survivor must raise DistTimeout (rc
    17) with thread stacks dumped once SHIFU_TPU_BARRIER_TIMEOUT_S
    expires."""
    import time

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SHIFU_TPU_BARRIER_TIMEOUT_S"] = "6"
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--port", str(port),
             "--nproc", "2", "--pid", str(i),
             "--out", str(tmp_path / "unused.npz"),
             "--local-devices", "1", "--mode", "barrier-stall"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for i in range(2)
    ]
    try:
        try:
            _, se0 = procs[0].communicate(timeout=180)
        except subprocess.TimeoutExpired:
            pytest.fail("survivor hung past the barrier timeout "
                        "(watchdog failed)")
        elapsed = time.monotonic() - t0
        rc0 = procs[0].returncode
        assert rc0 == 17, f"survivor rc={rc0}:\n{se0[-3000:]}"
        assert "DIST_TIMEOUT" in se0, se0[-3000:]
        assert "thread stacks" in se0, se0[-3000:]
        assert elapsed < 150, f"took {elapsed:.0f}s — watchdog too slow"
    finally:
        for p in procs:
            p.kill()


def test_two_process_preemption_consensus_then_smaller_mesh_resume(tmp_path):
    """ISSUE-8 acceptance drill. Two processes loop over watched
    barriers; SIGTERM lands on process 0 ONLY. Its graceful_shutdown
    handler publishes the preempt marker; process 0 checkpoints and
    exits rc 75 at the next boundary, and process 1 must OBSERVE the
    marker from inside a watched collective and exit rc 75 as well —
    cluster-wide consensus, not one clean exit plus a peer dying of
    barrier timeout (rc 17/18). Then a 1-process 1-device run restores
    the 2-device checkpoint bitwise on the smaller mesh (the elastic
    restart)."""
    import signal
    import time

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SHIFU_TPU_BARRIER_TIMEOUT_S"] = "30"
    env["SHIFU_TPU_PREEMPT_GRACE_S"] = "2"
    out = str(tmp_path / "drill.npz")
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--port", str(port),
             "--nproc", "2", "--pid", str(i), "--out", out,
             "--local-devices", "2", "--mode", "preempt-drill"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for i in range(2)
    ]
    ready = str(tmp_path / "drill.ready")
    try:
        while not os.path.exists(ready):
            if time.monotonic() - t0 > 120:
                for q in procs:
                    q.kill()
                pytest.fail("drill never reached the first barrier")
            if any(p.poll() is not None for p in procs):
                outs = [p.communicate() for p in procs]
                pytest.fail(f"worker died before the drill: {outs}")
            time.sleep(0.1)
        procs[0].send_signal(signal.SIGTERM)
        outs = []
        for p in procs:
            try:
                so, se = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("a process hung after the preemption — "
                            "consensus failed")
            outs.append((p.returncode, so, se))
    finally:
        for p in procs:
            p.kill()
    for i, (rc, _, se) in enumerate(outs):
        assert rc == 75, f"proc {i} rc={rc} (want 75):\n{se[-3000:]}"
        assert "PREEMPT_EXIT" in se, f"proc {i}:\n{se[-3000:]}"
    # the SIGTERM'd writer checkpointed before exiting, sidecar included
    ckpt_dir = str(tmp_path / "ckpt")
    steps = [n for n in os.listdir(ckpt_dir) if n.startswith("step_")]
    assert steps, os.listdir(str(tmp_path))
    assert any(n.endswith(".sharding.json") for n in steps), steps

    # elastic restart: 1 process × 1 device restores the 2-device state
    p = subprocess.Popen(
        [sys.executable, WORKER, "--port", str(_free_port()),
         "--nproc", "1", "--pid", "0", "--out", out,
         "--local-devices", "1", "--mode", "preempt-resume"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        so, se = p.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        p.kill()
        pytest.fail("smaller-mesh resume hung")
    assert p.returncode == 0, f"resume rc={p.returncode}:\n{se[-3000:]}"
    assert "RESUMED" in se, se[-3000:]


def test_writer_guard_never_initializes_backend(monkeypatch):
    """is_writer/writer_barrier are called from pure FILE operations
    (shifu init writing ColumnConfig.json); they must not lazily
    initialize a JAX backend — on a machine with an unreachable
    accelerator plugin that means hanging a command that never needed
    a device."""
    from jax._src import xla_bridge

    from shifu_tpu.parallel import dist

    def boom(*a, **k):
        raise AssertionError("backend initialization attempted")

    monkeypatch.setattr(xla_bridge, "get_backend", boom)
    assert dist.is_writer() is True
    dist.writer_barrier("t")   # no-op, no backend touch
    with dist.single_writer("t2") as w:
        assert w is True


def test_data_shard_rejects_junk_values(monkeypatch):
    """A typo ('ture') or an attempted shard count ('2') must raise,
    not silently ENABLE sharding — only the documented spellings are
    accepted; the shard count always comes from jax.process_count()."""
    from shifu_tpu.parallel import dist

    for bad in ("ture", "2", "both"):
        monkeypatch.setenv("SHIFU_TPU_DATA_SHARD", bad)
        with pytest.raises(ValueError, match="SHIFU_TPU_DATA_SHARD"):
            dist.data_shard()
    for off in ("0", "off", "false", "no", "OFF"):
        monkeypatch.setenv("SHIFU_TPU_DATA_SHARD", off)
        assert dist.data_shard() is None
    monkeypatch.setenv("SHIFU_TPU_DATA_SHARD", "auto")
    assert dist.data_shard() is None   # single process: no peers


def test_merge_keyed_striped_single_process_fold_order():
    """One-host contract of the striped merge: contributions replay in
    ascending global (file, chunk) key order, the extra payload
    reaches the fold, and a chunk key beyond the declared file range
    raises instead of being silently dropped."""
    from shifu_tpu.parallel import dist

    items = [((0, 0), 1.0), ((0, 1), 2.0), ((1, 0), 4.0), ((2, 0), 8.0)]
    seen = []

    def fold(acc, key, c, extra):
        assert extra == "names"
        seen.append(key)
        return (acc or 0.0) + c

    acc, extra = dist.merge_keyed_striped(
        "t.merge", (0, 1), 3, iter(items), fold,
        extra_fn=lambda: "names")
    assert acc == 15.0
    assert extra == "names"
    assert seen == [k for k, _ in items]

    with pytest.raises(RuntimeError, match="beyond the declared"):
        dist.merge_keyed_striped(
            "t.merge2", (0, 1), 1, iter(items),
            lambda acc, key, c, extra: acc)


def _stats_workspace(tmp_path):
    """An init-ed synthetic model set whose raw table spans several
    part files, so a 2-host shard genuinely splits the read."""
    from tests.synth import make_model_set
    from shifu_tpu.cli import main as cli_main

    rng = np.random.default_rng(20260807)
    root = make_model_set(tmp_path, rng, n_rows=2000)
    data_dir = os.path.join(root, "data")
    src = os.path.join(data_dir, "part-00000")
    with open(src) as f:
        lines = f.readlines()
    os.remove(src)
    n_parts = 4
    per = (len(lines) + n_parts - 1) // n_parts
    for i in range(n_parts):
        with open(os.path.join(data_dir, f"part-{i:05d}"), "w") as f:
            f.writelines(lines[i * per:(i + 1) * per])
    assert cli_main(["--dir", root, "init"]) == 0
    return root


# both sides must run the SAME parser (the native reader bypasses
# itself in sharded mode) and the SAME code path (streaming, small
# chunks → several per-chunk contributions per host, so the f64
# replay merge is actually exercised, not a single-chunk triviality)
_STATS_ENV = {"SHIFU_TPU_NATIVE_READER": "0",
              "SHIFU_TPU_STATS_CHUNK_ROWS": "300"}


def test_two_process_sharded_stats_bitwise_identical(tmp_path):
    """Pod-scale data-plane acceptance: `shifu stats` sharded over 2
    processes (each host streams only its own part files, partial
    sufficient statistics merged through the watched collectives) must
    write a ColumnConfig.json BITWISE identical to the 1-process
    sequential run — same bytes, not just close floats."""
    import hashlib
    import shutil

    base = _stats_workspace(tmp_path / "base")
    ws1 = str(tmp_path / "ws1" / "ModelSet")
    ws2 = str(tmp_path / "ws2" / "ModelSet")
    shutil.copytree(base, ws1)
    shutil.copytree(base, ws2)
    _run(1, ws1, local_devices=1, mode="stats", env_extra=_STATS_ENV)
    _run(2, ws2, local_devices=1, mode="stats", env_extra=_STATS_ENV)

    def sha(root):
        with open(os.path.join(root, "ColumnConfig.json"), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()

    assert sha(ws1) == sha(ws2), \
        "sharded stats diverged from the sequential run"


def test_two_process_sharded_correlation_bitwise_identical(tmp_path):
    """Correlation's sharded streaming path (per-chunk Pearson moments
    on the host-LOCAL mesh, striped f64 replay merge) must write a
    correlation.csv BITWISE identical to the 1-process streaming run.
    Also the regression test for the pod-desync bug: with 2 processes
    each host owns a different number of chunks, so any global-mesh
    step inside the per-chunk loop would hang or corrupt the merge."""
    import hashlib
    import shutil

    from shifu_tpu.cli import main as cli_main

    base = _stats_workspace(tmp_path / "base")
    # fill stats once, unsharded and in-process — both copies then
    # start from the identical stats-filled ColumnConfig (correlation
    # needs the binning vocabularies to encode categoricals)
    assert cli_main(["--dir", base, "stats"]) == 0
    ws1 = str(tmp_path / "ws1" / "ModelSet")
    ws2 = str(tmp_path / "ws2" / "ModelSet")
    shutil.copytree(base, ws1)
    shutil.copytree(base, ws2)
    env = dict(_STATS_ENV)
    # force the streaming path with several chunks per part file, so
    # both the local-mesh moment compute and the striped replay merge
    # are genuinely exercised
    env["SHIFU_TPU_ANALYSIS_CHUNK_ROWS"] = "300"
    _run(1, ws1, local_devices=1, mode="corr", env_extra=env)
    _run(2, ws2, local_devices=1, mode="corr", env_extra=env)

    def sha(root):
        p = os.path.join(root, "tmp", "Stats", "correlation.csv")
        with open(p, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()

    assert sha(ws1) == sha(ws2), \
        "sharded correlation diverged from the sequential run"


def test_two_process_sharded_ingest_matches_single_writer(tmp_path):
    """Sharded streaming ingest: 2 processes each own the row-log
    partitions ``k % 2 == pid`` (disjoint by construction, asserted
    from each worker's printed owned set) and append only the rows
    routed to their partitions. The merged window read of the
    2-process log must equal the 1-process single-writer log exactly —
    same rows, same deterministic (partition-ascending,
    segment-ascending) order."""
    from shifu_tpu.data.ingest import RowLog

    n_parts = 4
    root1 = str(tmp_path / "log1")
    root2 = str(tmp_path / "log2")
    for r in (root1, root2):
        RowLog(r, header=["a", "b"], partitions=n_parts,
               segment_rows=16)
    env = {"SHIFU_TPU_DATA_SHARD": "auto"}
    outs = _run(2, root2, local_devices=1, mode="ingest",
                env_extra=env)
    _run(1, root1, local_devices=1, mode="ingest", env_extra=env)

    owned = {}
    for rc, so, se in outs:
        for line in so.splitlines():
            if line.startswith("OWNED "):
                _, pid, parts = line.split(" ", 2)
                owned[int(pid)] = eval(parts)  # noqa: S307 — our print
    assert set(owned) == {0, 1}, owned
    assert not set(owned[0]) & set(owned[1]), "ownership overlaps"
    assert sorted(owned[0] + owned[1]) == list(range(n_parts))

    w1 = RowLog(root1).read_window("watch")
    w2 = RowLog(root2).read_window("watch")
    assert w1 is not None and len(w1.lines) == 240
    assert w2.lines == w1.lines, \
        "sharded-writer log diverged from the single-writer log"


def test_two_process_stats_survivor_escapes_midmerge_kill(tmp_path):
    """Mid-merge SIGKILL drill: process 1 dies INSIDE the first watched
    stats merge (fault site dist.allreduce_tree). The survivor must
    exit via the watchdog (rc 17, DistTimeout) or a fast collective
    failure (rc 18) — never hang. A clean rerun on the same workspace
    then succeeds."""
    import json
    import shutil
    import signal
    import time

    base = _stats_workspace(tmp_path / "base")
    ws = str(tmp_path / "ws" / "ModelSet")
    shutil.copytree(base, ws)
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(_STATS_ENV)
    env["SHIFU_TPU_BARRIER_TIMEOUT_S"] = "6"
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, "--port", str(port),
             "--nproc", "2", "--pid", str(i), "--out", ws,
             "--local-devices", "1", "--mode", "stats-kill"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            so, se = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("stats survivor hung after peer SIGKILL "
                        "(watched merge failed to escape)")
        outs.append((p.returncode, so, se))
    elapsed = time.monotonic() - t0
    rc1, _, se1 = outs[1]
    assert rc1 == -signal.SIGKILL, f"victim rc={rc1}:\n{se1[-2000:]}"
    rc0, _, se0 = outs[0]
    assert rc0 in (17, 18), f"survivor rc={rc0}:\n{se0[-3000:]}"
    assert "DIST_TIMEOUT" in se0 or "DIST_FAIL" in se0, se0[-3000:]
    assert elapsed < 150, f"took {elapsed:.0f}s — watchdog too slow"

    # the workspace is not poisoned: a clean sharded rerun completes
    # and commits a stats-filled ColumnConfig.json
    _run(2, ws, local_devices=1, mode="stats", env_extra=_STATS_ENV)
    with open(os.path.join(ws, "ColumnConfig.json")) as f:
        cols = json.load(f)
    assert any((c.get("columnStats") or {}).get("mean") is not None
               for c in cols), "rerun produced no stats"
