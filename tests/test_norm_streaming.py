"""Streaming (>RAM) norm: chunked two-pass mmap writer parity with the
resident path, exact hash-based validation split, and the fully
streaming pipeline (stats → norm → trainOnDisk train → eval)."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.processor import (init as init_proc, norm as norm_proc,
                                 stats as stats_proc)
from shifu_tpu.processor.base import ProcessorContext


def _prep(tmp_path, rng, n_rows=3000, **kw):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=n_rows, **kw)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["trainOnDisk"] = True
    mc["train"]["validSetRate"] = 0.2
    json.dump(mc, open(mcp, "w"))
    for proc in (init_proc, stats_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    return root


def test_streaming_norm_matches_resident_rows(tmp_path, rng, monkeypatch):
    root = _prep(tmp_path, rng)
    # resident
    monkeypatch.delenv("SHIFU_TPU_NORM_CHUNK_ROWS", raising=False)
    ctx = ProcessorContext.load(root)
    assert norm_proc.run(ctx) == 0
    nd = ctx.path_finder.normalized_data_path()
    res_dense = np.load(os.path.join(nd, "dense.npy"))
    res_tags = np.load(os.path.join(nd, "tags.npy"))
    # streaming
    monkeypatch.setenv("SHIFU_TPU_NORM_CHUNK_ROWS", "512")
    ctx = ProcessorContext.load(root)
    assert norm_proc.run(ctx) == 0
    st_dense = np.load(os.path.join(nd, "dense.npy"))
    st_tags = np.load(os.path.join(nd, "tags.npy"))

    # same multiset of rows, different order: sort by a stable key
    assert st_dense.shape == res_dense.shape
    assert st_tags.sum() == res_tags.sum()
    order_r = np.lexsort(res_dense.T)
    order_s = np.lexsort(st_dense.T)
    np.testing.assert_allclose(res_dense[order_r], st_dense[order_s],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(res_tags[order_r], st_tags[order_s])
    meta = json.load(open(os.path.join(nd, "meta.json")))
    vs = meta["validSplit"]
    assert vs["nTrain"] + vs["nVal"] == len(st_tags)
    # hash split is ~binomial around the configured rate
    assert abs(vs["nVal"] / len(st_tags) - 0.2) < 0.04
    # cleaned layout written too (tree path)
    cd = ctx.path_finder.cleaned_data_path()
    assert os.path.exists(os.path.join(cd, "dense.npy"))
    assert json.load(open(os.path.join(cd, "meta.json")))["streamingNorm"]


def test_norm_sampling_resident_streaming_parity(tmp_path, rng,
                                                 monkeypatch):
    """normalize.sampleRate drops rows in the norm output
    (NormalizeUDF DataSampler); sampleNegOnly keeps every positive;
    resident and streaming paths pick the IDENTICAL rows (stateless
    per-raw-row flags)."""
    root = _prep(tmp_path, rng)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["normalize"]["sampleRate"] = 0.5
    mc["normalize"]["sampleNegOnly"] = True
    json.dump(mc, open(mcp, "w"))

    monkeypatch.delenv("SHIFU_TPU_NORM_CHUNK_ROWS", raising=False)
    ctx = ProcessorContext.load(root)
    assert norm_proc.run(ctx) == 0
    nd = ctx.path_finder.normalized_data_path()
    res_dense = np.load(os.path.join(nd, "dense.npy"))
    res_tags = np.load(os.path.join(nd, "tags.npy"))

    monkeypatch.setenv("SHIFU_TPU_NORM_CHUNK_ROWS", "512")
    ctx = ProcessorContext.load(root)
    assert norm_proc.run(ctx) == 0
    st_dense = np.load(os.path.join(nd, "dense.npy"))
    st_tags = np.load(os.path.join(nd, "tags.npy"))

    # sampled down, but every positive kept (sampleNegOnly)
    full = _full_counts(root)
    assert len(res_tags) < full["rows"]
    assert res_tags.sum() == full["pos"]
    # identical row multiset across paths
    assert st_dense.shape == res_dense.shape
    np.testing.assert_allclose(
        res_dense[np.lexsort(res_dense.T)],
        st_dense[np.lexsort(st_dense.T)], rtol=1e-6, atol=1e-7)


def _full_counts(root):
    """Raw row/positive counts of the model set's training data."""
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.data.reader import read_raw_table, simple_column_name
    mc = ModelConfig.load(root)
    df = read_raw_table(mc)
    tgt = df[simple_column_name(
        mc.dataSet.targetColumnName.split("|")[0])].astype(str).str.strip()
    pos = tgt.isin(mc.pos_tags).sum()
    return {"rows": len(df), "pos": int(pos)}


def test_norm_sampling_rejected_for_multitask(tmp_path, rng):
    root = _prep(tmp_path, rng)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["normalize"]["sampleRate"] = 0.5
    mc["basic"]["multiTask"] = True
    tgt = mc["dataSet"]["targetColumnName"]
    mc["dataSet"]["targetColumnName"] = f"{tgt}|{tgt}"
    json.dump(mc, open(mcp, "w"))
    ctx = ProcessorContext.load(root)
    if not ctx.model_config.is_multi_task:
        pytest.skip("synth set cannot express a multi-task config")
    with pytest.raises(ValueError, match="multi-task"):
        norm_proc.run(ctx)


def test_streaming_norm_split_unbiased_on_sorted_input(tmp_path, rng,
                                                       monkeypatch):
    """Label-sorted input: the trailing val region is a uniform-random
    sample by construction (per-row hash), so its positive rate tracks
    the population."""
    root = _prep(tmp_path, rng)
    data_file = os.path.join(root, "data", "part-00000")
    lines = open(data_file).readlines()
    lines.sort(key=lambda ln: ln.rsplit("|", 1)[-1])
    open(data_file, "w").writelines(lines)
    for proc in (init_proc, stats_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    monkeypatch.setenv("SHIFU_TPU_NORM_CHUNK_ROWS", "400")
    ctx = ProcessorContext.load(root)
    assert norm_proc.run(ctx) == 0
    nd = ctx.path_finder.normalized_data_path()
    tags = np.load(os.path.join(nd, "tags.npy"))
    vs = json.load(open(os.path.join(nd, "meta.json")))["validSplit"]
    val_rate = float(tags[vs["nTrain"]:].mean())
    pop_rate = float(tags.mean())
    assert 0.6 * pop_rate < val_rate < 1.4 * pop_rate, (val_rate, pop_rate)


def test_fully_streaming_pipeline(tmp_path, rng, monkeypatch):
    """The complete >RAM pipeline: streaming stats → streaming norm →
    trainOnDisk NN and GBT → streaming eval — no step materializes the
    table."""
    from shifu_tpu.processor import eval as eval_proc, train as train_proc
    for alg, params in (
            ("NN", {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                    "ActivationFunc": ["tanh"], "LearningRate": 0.1,
                    "Propagation": "ADAM", "ChunkRows": 512}),
            ("GBT", {"TreeNum": 6, "MaxDepth": 3, "LearningRate": 0.3,
                     "ChunkRows": 512})):
        monkeypatch.setenv("SHIFU_TPU_STATS_CHUNK_ROWS", "600")
        monkeypatch.setenv("SHIFU_TPU_NORM_CHUNK_ROWS", "600")
        monkeypatch.setenv("SHIFU_TPU_EVAL_CHUNK_ROWS", "300")
        root = _prep(tmp_path / alg, rng, algorithm=alg,
                     train_params=params)
        for proc in (norm_proc, train_proc, eval_proc):
            ctx = ProcessorContext.load(root)
            assert proc.run(ctx) == 0
        perf = json.load(open(ProcessorContext.load(root)
                              .path_finder.eval_performance_path("Eval1")))
        assert perf["areaUnderRoc"] > 0.85, (alg, perf["areaUnderRoc"])
        assert perf["streaming"]["chunks"] > 1
        for k in ("SHIFU_TPU_STATS_CHUNK_ROWS", "SHIFU_TPU_NORM_CHUNK_ROWS",
                  "SHIFU_TPU_EVAL_CHUNK_ROWS"):
            monkeypatch.delenv(k, raising=False)


def test_float16_streaming_layout_halves_bytes(tmp_path, rng):
    """precisionType FLOAT16 + trainOnDisk: the dense block lands on
    disk as REAL f16 (the values are rounded through half precision
    anyway), the chunked trainer widens on device, and the pipeline
    still learns. Covers both layout writers (resident save_normalized
    and the chunked norm_streaming pass)."""
    import json

    from tests.synth import make_model_set
    from shifu_tpu.processor import (eval as eval_proc, init as init_proc,
                                     norm as norm_proc,
                                     stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    for mode, env in (("resident-writer", {}),
                      ("chunked-writer",
                       {"SHIFU_TPU_NORM_CHUNK_ROWS": "256",
                        "SHIFU_TPU_STATS_CHUNK_ROWS": "256"})):
        root = make_model_set(tmp_path / mode, np.random.default_rng(55),
                              n_rows=1500,
                              train_params={"NumHiddenLayers": 1,
                                            "NumHiddenNodes": [8],
                                            "ActivationFunc": ["tanh"],
                                            "LearningRate": 0.1,
                                            "Propagation": "ADAM",
                                            "ChunkRows": 256})
        mcp = os.path.join(root, "ModelConfig.json")
        mc = json.load(open(mcp))
        mc["train"]["trainOnDisk"] = True
        mc["normalize"]["precisionType"] = "FLOAT16"
        json.dump(mc, open(mcp, "w"))
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            for proc in (init_proc, stats_proc, norm_proc, train_proc):
                ctx = ProcessorContext.load(root)
                assert proc.run(ctx) == 0, mode
            ctx = ProcessorContext.load(root)
            assert eval_proc.run(ctx) == 0, mode
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        dense = np.load(os.path.join(
            ctx.path_finder.normalized_data_path(), "dense.npy"),
            mmap_mode="r")
        assert dense.dtype == np.float16, (mode, dense.dtype)
        perf = json.load(open(
            ctx.path_finder.eval_performance_path("Eval1")))
        assert perf["areaUnderRoc"] > 0.85, (mode, perf["areaUnderRoc"])
