"""Trace-plane tests (tier-1): span recording semantics, ring-buffer
accounting, the disabled-path zero-cost contract, Perfetto export +
cross-host merge, serving span/timing parity, the /metrics exposition,
and the acceptance drill — a traced `shifu test` DAG run yields one
merged trace with a span per node, correctly parented.
"""

import gc
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from shifu_tpu.cli import main as cli_main
from shifu_tpu.obs import trace as obs_trace
from shifu_tpu.profiling import TRACE_FIELDS


@pytest.fixture(autouse=True)
def _trace_isolation(monkeypatch):
    """Every test starts with tracing off and no inherited workspace;
    a test that enables tracing does so explicitly."""
    monkeypatch.delenv("SHIFU_TPU_TRACE", raising=False)
    monkeypatch.delenv("SHIFU_TPU_TRACE_DIR", raising=False)
    monkeypatch.delenv("SHIFU_TPU_TRACE_BUF", raising=False)
    assert obs_trace._RUN is None
    yield
    obs_trace._RUN = None


# ---------------------------------------------------------------------------
# span semantics
# ---------------------------------------------------------------------------

def test_span_nesting_parentage_and_attrs(tmp_path, monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    with obs_trace.trace_run(str(tmp_path), "train") as run:
        assert obs_trace.active()
        with obs_trace.span("ckpt.stage", step=7) as outer:
            with obs_trace.span("ckpt.publish", step=7) as inner:
                pass
        rid = obs_trace.record_span("input.h2d", 1.0, 1.5, bytes=64)
    spans = {s["id"]: s for s in run.tracer.spans()}
    o, i = spans[outer.id], spans[inner.id]
    assert i["parent"] == outer.id
    assert o["parent"] == run.tracer.root_id
    assert o["args"] == {"step": 7}
    assert spans[rid]["name"] == "input.h2d"
    assert spans[rid]["args"] == {"bytes": 64}
    assert spans[rid]["dur"] == pytest.approx(0.5)
    # the root run.step span closed last, carrying the step attr
    root = spans[run.tracer.root_id]
    assert root["name"] == "run.step" and root["parent"] is None
    assert root["args"] == {"step": "train"}


def test_span_error_attr_recorded(tmp_path, monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    with obs_trace.trace_run(str(tmp_path), "train") as run:
        with pytest.raises(ValueError):
            with obs_trace.span("ckpt.stage") as sp:
                raise ValueError("boom")
    rec = {s["id"]: s for s in run.tracer.spans()}[sp.id]
    assert "boom" in rec["args"]["error"]


def test_ring_buffer_drops_oldest_and_counts(tmp_path):
    tr = obs_trace.Tracer("r", str(tmp_path), True, cap=8)
    ids = []
    for _ in range(20):
        sid = tr.new_id()
        ids.append(sid)
        tr.closed(sid, "input.h2d", None, 0.0, 0.001, {})
    kept = tr.spans()
    assert len(kept) == 8
    assert [s["id"] for s in kept] == ids[-8:]   # oldest dropped
    s = tr.summary()
    assert tuple(s) == TRACE_FIELDS
    assert s["span_count"] == 20
    assert s["dropped_spans"] == 12


def test_summary_top_self_excludes_child_time(tmp_path):
    tr = obs_trace.Tracer("r", str(tmp_path), True, cap=100)
    parent = tr.new_id()
    child = tr.new_id()
    tr.closed(child, "ckpt.publish", parent, 0.0, 0.9, {})
    tr.closed(parent, "ckpt.stage", None, 0.0, 1.0, {})
    top = {t["name"]: t["self_s"] for t in tr.summary()["top_self"]}
    assert top["ckpt.publish"] == pytest.approx(0.9, abs=1e-6)
    assert top["ckpt.stage"] == pytest.approx(0.1, abs=1e-6)


def test_open_spans_cited_by_watchdog_dump(tmp_path, monkeypatch):
    from shifu_tpu import resilience
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    with obs_trace.trace_run(str(tmp_path), "train"):
        with obs_trace.span("dist.collective", tag="allgather"):
            names = [s["name"] for s in obs_trace.open_spans()]
            assert "dist.collective" in names
            dump = resilience.dump_thread_stacks("test probe")
            assert "open spans:" in dump
            assert "dist.collective" in dump


# ---------------------------------------------------------------------------
# disabled path: zero files, bounded overhead
# ---------------------------------------------------------------------------

def test_disabled_records_nothing_and_writes_no_files(tmp_path):
    with obs_trace.trace_run(str(tmp_path), "train") as run:
        assert run is None
        assert not obs_trace.active()
        assert obs_trace.span("input.h2d") is obs_trace._NOOP
        assert obs_trace.record_span("input.h2d", 0.0, 1.0) is None
        assert obs_trace.open_spans() == []
    assert not os.path.exists(os.path.join(str(tmp_path), "tmp", "trace"))


def _work():
    s = 0
    for i in range(4000):
        s += i * i
    return s


def test_disabled_span_overhead_under_5_percent():
    """The ISSUE gate: with the knob unset, wrapping the work in
    span() must cost ≤5% over the untraced loop. Plain/traced reps are
    interleaved (both sides see the same machine conditions), compared
    best-of-15 against best-of-15 so GC pauses and scheduler
    preemptions fall out of the minima, with up to three attempts —
    the gate asserts the capability (true disabled-path cost is ~0.1%
    here), not the worst case of a noisy shared box."""
    assert not obs_trace.active()
    n = 100

    def plain():
        t0 = time.perf_counter()
        for _ in range(n):
            _work()
        return time.perf_counter() - t0

    def traced():
        t0 = time.perf_counter()
        for _ in range(n):
            with obs_trace.span("input.h2d"):
                _work()
        return time.perf_counter() - t0

    plain(), traced()   # warm both paths
    last = None
    for _attempt in range(3):
        bases, wraps = [], []
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for _ in range(15):
                bases.append(plain())
                wraps.append(traced())
        finally:
            if gc_was_enabled:
                gc.enable()
        last = (min(wraps), min(bases))
        if last[0] <= last[1] * 1.05:
            return
    assert last[0] <= last[1] * 1.05, last


# ---------------------------------------------------------------------------
# export + merge
# ---------------------------------------------------------------------------

def test_export_writes_wellformed_chronological_perfetto_json(
        tmp_path, monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    with obs_trace.trace_run(str(tmp_path), "train") as run:
        for i in range(5):
            obs_trace.record_span("input.host_parse",
                                  10.0 - i, 10.5 - i, chunk=i)
    out = os.path.join(str(tmp_path), "tmp", "trace",
                       f"{run.run_id}.trace.json")
    assert os.path.exists(out)
    with open(out, encoding="utf-8") as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert len(events) == 6   # 5 parses + the run.step root
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    for e in events:
        assert e["ph"] == "X" and e["dur"] >= 1
        assert e["cat"] == e["name"].split(".", 1)[0]
        assert "id" in e["args"]
    # per-process span file kept alongside the merge
    assert os.path.exists(os.path.join(
        str(tmp_path), "tmp", "trace", run.run_id,
        f"spans.{os.getpid()}.jsonl"))


def test_two_host_merge_orders_by_corrected_clocks(tmp_path):
    tdir = str(tmp_path / "run1")
    os.makedirs(tdir)

    def _host(pid, offset, ts, name):
        with open(os.path.join(tdir, f"spans.{pid}.jsonl"), "w") as f:
            f.write(json.dumps({"clock": {"pid": pid,
                                          "offset_s": offset}}) + "\n")
            f.write(json.dumps({"id": f"{pid}:1", "parent": None,
                                "name": name, "ts": ts, "dur": 0.5,
                                "pid": pid, "tid": 1,
                                "thread": "MainThread"}) + "\n")

    # host B's clock runs 5s ahead: its raw ts is later but its
    # corrected time is EARLIER than host A's span
    _host(100, 0.0, 100.0, "dist.collective")
    _host(200, 5.0, 104.0, "dag.node")
    out = os.path.join(str(tmp_path), "merged.trace.json")
    doc = obs_trace.merge_trace(tdir, out)
    names = [e["name"] for e in doc["traceEvents"]]
    assert names == ["dag.node", "dist.collective"]
    assert doc["traceEvents"][0]["ts"] == int(99.0 * 1e6)
    with open(out, encoding="utf-8") as f:
        assert json.load(f) == doc


def test_participant_mode_exports_but_never_merges(tmp_path, monkeypatch):
    """With SHIFU_TPU_TRACE_DIR inherited (DAG subprocess node, remote
    host), trace_run adopts the coordinator's workspace + run_id and
    leaves merging to the coordinator."""
    tdir = str(tmp_path / "tmp" / "trace" / "shared-run")
    os.makedirs(tdir)
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    monkeypatch.setenv("SHIFU_TPU_TRACE_DIR", tdir)
    with obs_trace.trace_run(str(tmp_path), "norm") as run:
        assert run.run_id == "shared-run"
        assert not run.tracer.coordinator
    assert os.path.exists(os.path.join(
        tdir, f"spans.{os.getpid()}.jsonl"))
    assert not os.path.exists(tdir + ".trace.json")
    # participants must not pop the coordinator's exported knob
    assert os.environ.get("SHIFU_TPU_TRACE_DIR") == tdir


def test_export_failure_never_fails_the_step(tmp_path, monkeypatch):
    from shifu_tpu import resilience
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    monkeypatch.setenv("SHIFU_TPU_FAULT", "obs.export:oserror:1")
    resilience.reset_faults()
    try:
        with obs_trace.trace_run(str(tmp_path), "train") as run:
            obs_trace.record_span("input.h2d", 0.0, 1.0)
        # absorbed: no exception escaped, no merged trace either
        assert not os.path.exists(os.path.join(
            str(tmp_path), "tmp", "trace",
            f"{run.run_id}.trace.json"))
    finally:
        monkeypatch.delenv("SHIFU_TPU_FAULT")
        resilience.reset_faults()


# ---------------------------------------------------------------------------
# serving span parity + /metrics
# ---------------------------------------------------------------------------

def test_serving_spans_match_submit_timed_splits(tmp_path, monkeypatch):
    from tests.test_serve import _tiny_nn_dir
    from shifu_tpu.serve.service import ScorerService

    models = _tiny_nn_dir(str(tmp_path / "models"))
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    with obs_trace.trace_run(str(tmp_path), "serve") as run:
        with ScorerService(models_dir=models, max_delay=0.005,
                           aot_compile=False) as svc:
            _, timing = svc.submit_timed(
                dense=np.zeros((3, 12), np.float32), timeout=60.0)
        spans = run.tracer.spans()
    req = [s for s in spans if s["name"] == "serve.request"]
    assert len(req) == 1
    children = {s["name"]: s for s in spans
                if s.get("parent") == req[0]["id"]}
    assert set(children) == {"serve.queue", "serve.pad", "serve.h2d",
                             "serve.device", "serve.d2h"}
    # spans are cut from the SAME timestamps the timing dict is
    # computed from — durations agree exactly, not approximately
    for stage in ("queue", "pad", "h2d", "device", "d2h"):
        assert children[f"serve.{stage}"]["dur"] == pytest.approx(
            timing[f"{stage}_s"], abs=1e-9), stage
    assert req[0]["dur"] == pytest.approx(timing["total_s"], abs=1e-9)
    flush = [s for s in spans if s["name"] == "serve.flush"]
    assert flush and flush[0]["args"]["requests"] == 1
    # synthetic track: every serving span rides the "serve" track
    assert req[0]["thread"] == "serve"


def test_metrics_endpoint_parses_as_prometheus_text(tmp_path):
    from tests.test_serve import _tiny_nn_dir
    from shifu_tpu.serve.http import HttpFrontEnd
    from shifu_tpu.serve.service import ScorerService

    models = _tiny_nn_dir(str(tmp_path / "models"))
    with ScorerService(models_dir=models, max_delay=0.005,
                       aot_compile=False) as svc:
        svc.submit(dense=np.zeros((2, 12), np.float32), timeout=60.0)
        front = HttpFrontEnd(svc, host="127.0.0.1", port=0).start()
        try:
            host, port = front.address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=30) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                body = r.read().decode()
        finally:
            front.close()
    samples = {}
    for line in body.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE "))
            continue
        name_labels, value = line.rsplit(" ", 1)
        samples[name_labels] = float(value)   # every sample parses
    assert samples["shifu_serve_requests_total"] == 1.0
    assert samples["shifu_serve_rows_total"] == 2.0
    assert 'shifu_serve_latency_ms{quantile="0.5"}' in samples


# ---------------------------------------------------------------------------
# acceptance: traced DAG run, steps.jsonl block, CLI surfaces
# ---------------------------------------------------------------------------

def _tiny_model_set(tmp_path):
    # a PRIVATE generator: drawing from the session-scoped `rng`
    # fixture here would shift the stream under the golden-file tests
    # that share it
    from tests.synth import make_model_set
    return make_model_set(tmp_path, np.random.default_rng(7), n_rows=300)


def test_traced_dag_run_produces_merged_trace_with_node_parentage(
        tmp_path, monkeypatch, capsys):
    model_set = _tiny_model_set(tmp_path)
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    assert cli_main(["--dir", model_set, "test"]) == 0
    monkeypatch.delenv("SHIFU_TPU_TRACE")

    import glob
    merged = glob.glob(os.path.join(model_set, "tmp", "trace",
                                    "*.trace.json"))
    assert len(merged) == 1
    with open(merged[0], encoding="utf-8") as f:
        events = json.load(f)["traceEvents"]
    roots = [e for e in events if e["name"] == "run.step"]
    assert len(roots) == 1
    root_id = roots[0]["args"]["id"]
    nodes = [e for e in events if e["name"] == "dag.node"]
    assert {e["args"]["node"] for e in nodes} == {
        "test.config", "test.filter", "test.eval.Eval1", "test.plan"}
    node_ids = set()
    for e in nodes:
        assert e["args"]["parent"] == root_id
        assert e["args"]["state"] == "done"
        node_ids.add(e["args"]["id"])
    for kid in (e for e in events if e["name"] in ("dag.queue",
                                                   "dag.run")):
        assert kid["args"]["parent"] in node_ids

    # the step record carries the TRACE_FIELDS summary block
    steps = os.path.join(model_set, "tmp", "metrics", "steps.jsonl")
    recs = [json.loads(l) for l in open(steps, encoding="utf-8")
            if l.strip()]
    traced = [r for r in recs if r["step"] == "test" and "trace" in r]
    assert traced, "no steps.jsonl record carries a trace block"
    block = traced[-1]["trace"]
    assert tuple(block) == TRACE_FIELDS
    assert block["span_count"] >= 1 + 3 * len(nodes)
    assert block["dropped_spans"] == 0

    # knob stayed unset for the untraced rerun → no NEW trace files
    assert cli_main(["--dir", model_set, "test"]) == 0
    assert glob.glob(os.path.join(model_set, "tmp", "trace",
                                  "*.trace.json")) == merged

    # CLI surfaces: `trace ls` pairs the run's artifacts, `top` renders
    # the step records with the trace summary
    capsys.readouterr()
    assert cli_main(["--dir", model_set, "trace", "ls"]) == 0
    out = capsys.readouterr().out
    run_id = os.path.basename(merged[0])[:-len(".trace.json")]
    assert run_id in out and "run_id" in out
    assert cli_main(["--dir", model_set, "top"]) == 0
    out = capsys.readouterr().out
    assert "test" in out and "dag.run" in out


def test_profile_output_named_after_trace_run_id(tmp_path, monkeypatch):
    """maybe_profile's directory and the span trace share a run_id so
    `shifu trace ls` pairs device and host traces."""
    monkeypatch.setenv("SHIFU_TPU_TRACE", "1")
    with obs_trace.trace_run(str(tmp_path), "train") as run:
        assert obs_trace.current_run_id("train") == run.run_id
    rows = obs_trace.trace_ls(str(tmp_path))
    assert [r["run_id"] for r in rows] == [run.run_id]
    assert rows[0]["trace"] and rows[0]["span_files"] == 1
    # untraced: a fresh id still namespaced by step + pid
    rid = obs_trace.current_run_id("eval")
    assert rid.startswith("eval-") and rid.endswith(str(os.getpid()))
