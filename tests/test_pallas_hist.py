"""Fused bin-lookup + histogram kernel parity (ops/pallas_hist).

The fused kernel (`level_histograms_fused`) re-derives bin indices
in-register from raw feature values + cut boundaries instead of reading
a pre-binned int32 matrix. These tests pin the whole contract on CPU:

- the in-kernel binning rule (`bins_from_values`, also the XLA-fallback
  binning stage) matches `gbdt.bin_dataset` bit-for-bit, including NaN
  missing values and host-mapped categorical codes;
- the fused kernel's histograms (interpret mode) match the XLA
  scatter-add reference, in both default and
  SHIFU_TPU_HIST_PRECISION=highest modes;
- a full GBT build through FusedBins grows the same ensemble as the
  pre-binned path on the SAME histogram backend (cross-backend runs may
  legitimately flip `default_left` on equal-gain ties — float summation
  order — so parity is only asserted same-backend).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tpu.models import gbdt
from shifu_tpu.models.gbdt import TreeConfig
from shifu_tpu.ops.pallas_hist import (bins_from_values,
                                       level_histograms_fused)

N_BINS = 10


def _dataset(rng, n=500, cn=3, vocabs=(5, 3)):
    """Mixed numeric + categorical data with missing values, plus the
    packed bin tables: numeric cuts are per-column quantiles (+inf
    padded to n_bins-2 slots), categorical maps are posRate-style
    permutations of the low bin ids."""
    dense = rng.normal(0.0, 1.0, (n, cn)).astype(np.float32)
    dense[rng.random((n, cn)) < 0.1] = np.nan
    k = N_BINS - 2
    qs = np.linspace(0.1, 0.9, k - 2)
    cuts = np.full((k, cn), np.inf, np.float32)
    cuts[:k - 2] = np.nanquantile(dense, qs, axis=0)
    cat_orders = [rng.permutation(v).astype(np.int32) for v in vocabs]
    codes = np.stack([rng.integers(-1, v + 1, n) for v in vocabs],
                     axis=1).astype(np.int32)  # -1 and v are missing
    tables = gbdt.make_bin_tables(cuts, cat_orders, N_BINS)
    return dense, codes, tables


def test_bins_from_values_matches_bin_dataset(rng):
    """The lax reference for the kernel's in-register binning agrees
    with the host bin_dataset on every cell: numeric quantile lookups,
    NaN -> missing bin, categorical identity-cut trick (host-mapped id
    carried as a float against cuts 0.5, 1.5, ...)."""
    dense, codes, tables = _dataset(rng)
    ref = gbdt.bin_dataset(tables, dense, codes, N_BINS)        # (R, C)
    fused = gbdt.make_fused_inputs(tables, dense, codes, N_BINS)
    got = np.asarray(bins_from_values(jnp.asarray(fused.valuesT),
                                      jnp.asarray(fused.cuts), N_BINS))
    np.testing.assert_array_equal(got.T, ref)


def _scatter_ref(binsT, slot, grad, hess, n_slots, n_bins):
    """Numpy mirror of the XLA scatter in _local_level_histograms."""
    c, r = binsT.shape
    g = np.zeros((n_slots, c, n_bins), np.float32)
    h = np.zeros((n_slots, c, n_bins), np.float32)
    ok = (slot >= 0) & (slot < n_slots)
    for col in range(c):
        np.add.at(g[:, col, :], (slot[ok], binsT[col, ok]), grad[ok])
        np.add.at(h[:, col, :], (slot[ok], binsT[col, ok]), hess[ok])
    return g, h


def _fused_case(rng, n=600):
    dense, codes, tables = _dataset(rng, n=n)
    fused = gbdt.make_fused_inputs(tables, dense, codes, N_BINS)
    bins = gbdt.bin_dataset(tables, dense, codes, N_BINS)
    n_slots = 4
    slot = rng.integers(-1, n_slots + 2, n).astype(np.int32)
    grad = rng.normal(0, 1, n).astype(np.float32)
    hess = rng.uniform(0.5, 1.5, n).astype(np.float32)
    return fused, bins, slot, grad, hess, n_slots


def test_fused_kernel_matches_scatter_reference(rng):
    """level_histograms_fused (interpret mode) == scatter-add on the
    equivalent pre-binned matrix, for rows scattered across level
    slots including out-of-level (-1, >=S dump) rows."""
    fused, bins, slot, grad, hess, n_slots = _fused_case(rng)
    g0, h0 = _scatter_ref(bins.T, slot, grad, hess, n_slots, N_BINS)
    g1, h1 = level_histograms_fused(
        jnp.asarray(fused.valuesT), jnp.asarray(fused.cuts),
        jnp.asarray(slot), jnp.asarray(grad), jnp.asarray(hess),
        n_slots, N_BINS, row_tile=128, col_tile=5, interpret=True)
    np.testing.assert_allclose(np.asarray(g1), g0, rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(h1), h0, rtol=1e-5, atol=1e-3)


def test_fused_kernel_highest_precision(rng, monkeypatch):
    """SHIFU_TPU_HIST_PRECISION=highest switches the fused kernel to
    the f32-exact contraction (small row tile); parity with the
    scatter reference tightens to summation-order noise."""
    monkeypatch.setenv("SHIFU_TPU_HIST_PRECISION", "highest")
    fused, bins, slot, grad, hess, n_slots = _fused_case(rng)
    g0, h0 = _scatter_ref(bins.T, slot, grad, hess, n_slots, N_BINS)
    g1, h1 = level_histograms_fused(
        jnp.asarray(fused.valuesT), jnp.asarray(fused.cuts),
        jnp.asarray(slot), jnp.asarray(grad), jnp.asarray(hess),
        n_slots, N_BINS, interpret=True)
    np.testing.assert_allclose(np.asarray(g1), g0, rtol=1e-6, atol=1e-4)
    np.testing.assert_allclose(np.asarray(h1), h0, rtol=1e-6, atol=1e-4)


def _tree_arrays(trees):
    return {k: np.asarray(v) for k, v in trees.items()}


def _assert_same_ensemble(a, b):
    for key in ("feature", "bin", "is_leaf", "default_left"):
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)
    np.testing.assert_allclose(a["leaf_value"], b["leaf_value"],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_fused_gbt_matches_prebinned_same_backend(rng, monkeypatch,
                                                  backend):
    """build_gbt fed FusedBins grows the same trees as build_gbt fed
    the pre-binned int32 matrix, holding the histogram backend fixed
    (xla scatter, or the pallas kernels in interpret mode on CPU).
    SHIFU_TPU_HIST is read at trace time, so caches are cleared around
    the env flip."""
    n, cn = 800, 5
    dense = rng.normal(0.0, 1.0, (n, cn)).astype(np.float32)
    k = N_BINS - 2
    cuts = np.quantile(dense, np.linspace(0.08, 0.92, k),
                       axis=0).astype(np.float32)
    beta = rng.normal(0, 1, cn)
    y = ((dense @ beta) > np.median(dense @ beta)).astype(np.float32)
    w = np.ones(n, np.float32)
    tables = gbdt.make_bin_tables(cuts, [], N_BINS)
    bins = gbdt.bin_dataset(tables, dense, None, N_BINS)
    fused = gbdt.make_fused_inputs(tables, dense, None, N_BINS)

    cfg = TreeConfig(max_depth=3, n_bins=N_BINS, learning_rate=0.3,
                     loss="log")
    monkeypatch.setenv("SHIFU_TPU_HIST", backend)
    jax.clear_caches()
    try:
        t_int, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=4)
        t_fused, _ = gbdt.build_gbt(cfg, fused, y, w, n_trees=4)
    finally:
        jax.clear_caches()  # don't leak the pinned backend's traces

    _assert_same_ensemble(_tree_arrays(t_int), _tree_arrays(t_fused))
