"""Fused normalize + first-layer scoring kernel parity (ops/pallas_score).

The scoring path's z-scored matrix is written once and read once;
`fused_first_layer` folds the z-score into the first-layer contraction.
These tests pin the contract on CPU (interpret mode): the Pallas route
must match the XLA route (which is itself `normalize.zscore` + matmul,
the lax reference), including the tiny-std column rule, NaN -> mean ->
exact 0, and the mean ± cutoff·std clamp; `score_nn` must match
`nn.forward` over pre-normalized inputs; and `scorer.score_matrix` must
return the same scores whether or not the fused route is engaged.
"""

import jax
import jax.numpy as jnp
import numpy as np

from shifu_tpu.models import nn as nn_mod
from shifu_tpu.ops import pallas_score
from shifu_tpu.ops.normalize import STD_EPS, zscore

CUTOFF = 4.0


def _norm_case(rng, n=300, c=20, h=16):
    """Raw values with missing cells, a tiny-std column (index 3), and
    outliers beyond the cutoff clamp."""
    values = rng.normal(2.0, 3.0, (n, c)).astype(np.float32)
    values[rng.random((n, c)) < 0.1] = np.nan
    values[:5, 0] = 1e6                        # beyond the clamp
    mean = rng.normal(0, 1, c).astype(np.float32)
    std = rng.uniform(0.5, 2.0, c).astype(np.float32)
    std[3] = STD_EPS / 10                      # tiny-std -> exact 0
    w = rng.normal(0, 0.3, (c, h)).astype(np.float32)
    b = rng.normal(0, 0.1, h).astype(np.float32)
    return (jnp.asarray(values), jnp.asarray(mean), jnp.asarray(std),
            jnp.asarray(w), jnp.asarray(b))


def test_fused_first_layer_matches_xla(rng):
    values, mean, std, w, b = _norm_case(rng)
    ref = pallas_score.fused_first_layer(values, mean, std, CUTOFF, w, b,
                                         mode="xla")
    got = pallas_score.fused_first_layer(values, mean, std, CUTOFF, w, b,
                                         mode="pallas", row_tile=64,
                                         col_tile=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-5)


def test_fused_tiny_std_column_contributes_zero(rng):
    """A column with std < STD_EPS must land on EXACTLY 0 in-register
    (lo = hi = mean collapses the clamp), so wild values there change
    nothing: the output equals the bias when every column is tiny."""
    n, c, h = 64, 6, 8
    values = jnp.asarray(rng.normal(0, 100, (n, c)).astype(np.float32))
    mean = jnp.zeros(c, jnp.float32)
    std = jnp.full(c, STD_EPS / 2, jnp.float32)
    w = jnp.asarray(rng.normal(0, 1, (c, h)).astype(np.float32))
    b = jnp.asarray(rng.normal(0, 1, h).astype(np.float32))
    out = pallas_score.fused_first_layer(values, mean, std, CUTOFF, w, b,
                                         mode="pallas", interpret=True)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.broadcast_to(np.asarray(b), (n, h)))


def test_fused_nan_rows_equal_mean_rows(rng):
    """NaN (missing) fills to the column mean, i.e. z = 0 — an all-NaN
    row scores identically to a row carrying the means verbatim."""
    c, h = 10, 4
    mean = jnp.asarray(rng.normal(0, 1, c).astype(np.float32))
    std = jnp.asarray(rng.uniform(0.5, 2.0, c).astype(np.float32))
    w = jnp.asarray(rng.normal(0, 1, (c, h)).astype(np.float32))
    b = jnp.zeros(h, jnp.float32)
    values = jnp.stack([jnp.full(c, jnp.nan), mean])
    out = np.asarray(pallas_score.fused_first_layer(
        values, mean, std, CUTOFF, w, b, mode="pallas", interpret=True))
    np.testing.assert_array_equal(out[0], out[1])


def test_score_nn_matches_forward_on_normalized(rng):
    """Full fused MLP forward over RAW values == nn.forward over the
    materialized z-scored matrix."""
    c = 12
    spec = nn_mod.MLPSpec(input_dim=c, hidden_dims=(16, 8),
                          activations=("relu", "tanh"))
    params = nn_mod.init_params(spec, jax.random.PRNGKey(7))
    values = rng.normal(1.0, 2.0, (200, c)).astype(np.float32)
    values[rng.random((200, c)) < 0.15] = np.nan
    mean = jnp.asarray(rng.normal(0, 1, c).astype(np.float32))
    std = jnp.asarray(rng.uniform(0.5, 2.0, c).astype(np.float32))
    z = zscore(jnp.asarray(values), mean, std, CUTOFF)
    ref = nn_mod.forward(spec, params, z)
    got = pallas_score.score_nn(spec, params, jnp.asarray(values), mean,
                                std, CUTOFF, mode="pallas",
                                interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_score_fused_mode_knob(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_SCORE_FUSED", "pallas")
    assert pallas_score.score_fused_mode() == "pallas"
    monkeypatch.setenv("SHIFU_TPU_SCORE_FUSED", "xla")
    assert pallas_score.score_fused_mode() == "xla"
    monkeypatch.delenv("SHIFU_TPU_SCORE_FUSED", raising=False)
    # auto resolves by backend: CPU tier-1 -> xla fallback
    if jax.default_backend() != "tpu":
        assert pallas_score.score_fused_mode() == "xla"


def test_score_matrix_fused_route_matches_plain(rng, monkeypatch):
    """scorer.score_matrix with a `norm` block + SHIFU_TPU_SCORE_FUSED=
    pallas (interpret on CPU) returns the same scores as the plain
    path reading the materialized normalized matrix."""
    from shifu_tpu.eval import scorer

    c = 9
    spec = nn_mod.MLPSpec(input_dim=c, hidden_dims=(8,),
                          activations=("relu",))
    params = nn_mod.init_params(spec, jax.random.PRNGKey(11))
    params = jax.tree.map(np.asarray, params)
    meta = {"spec": {"input_dim": c, "hidden_dims": [8],
                     "activations": ["relu"]}}
    raw = rng.normal(0.5, 1.5, (150, c)).astype(np.float32)
    raw[rng.random((150, c)) < 0.1] = np.nan
    mean = rng.normal(0, 1, c).astype(np.float32)
    std = rng.uniform(0.5, 2.0, c).astype(np.float32)
    dense = np.asarray(zscore(jnp.asarray(raw), jnp.asarray(mean),
                              jnp.asarray(std), CUTOFF))

    monkeypatch.delenv("SHIFU_TPU_SCORE_FUSED", raising=False)
    plain = scorer.score_matrix("nn", meta, params, dense)
    monkeypatch.setenv("SHIFU_TPU_SCORE_FUSED", "pallas")
    norm = {"mean": mean, "std": std, "cutoff": CUTOFF}
    fused = scorer.score_matrix("nn", meta, params, dense,
                                raw_dense=raw, norm=norm)
    np.testing.assert_allclose(fused, plain, rtol=1e-5, atol=1e-5)
