"""Fused GBT split-search kernel parity (ops/pallas_split).

`_best_splits`' XLA chain (cumsum → gain → masks → flat argmax) is the
reference; the Pallas kernel fuses the whole chain and must match it
EXACTLY on CPU (interpret mode) — including jnp.argmax's
first-occurrence tie-breaking across column tiles, the min-instances
and feature masks, the last-main-bin exclusion, and the all-masked
node resolving to flat index 0. The suite runs under the default and
`SHIFU_TPU_HIST_PRECISION=highest` knob settings (split math is pure
f32 elementwise either way; the knob gates the histogram kernel that
produces this kernel's inputs — parity must hold in both regimes).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tpu.models import gbdt
from shifu_tpu.models.gbdt import TreeConfig
from shifu_tpu.ops import pallas_split

CFG = TreeConfig(max_depth=4, n_bins=16, min_instances_per_node=2,
                 min_info_gain=0.0, reg_lambda=1.0, learning_rate=0.1,
                 loss="squared")


def _hists(rng, n, c, n_bins=16):
    g = rng.normal(size=(n, c, n_bins)).astype(np.float32)
    h = (np.abs(rng.normal(size=(n, c, n_bins))) * 3).astype(np.float32)
    return jnp.asarray(g), jnp.asarray(h)


def _xla_ref(g, h, fm, cfg=CFG):
    """The XLA chain, pinned regardless of the routing knob."""
    import os
    old = os.environ.get("SHIFU_TPU_SPLIT_FUSED")
    os.environ["SHIFU_TPU_SPLIT_FUSED"] = "xla"
    try:
        return gbdt._best_splits((g, h), cfg, fm)
    finally:
        if old is None:
            os.environ.pop("SHIFU_TPU_SPLIT_FUSED", None)
        else:
            os.environ["SHIFU_TPU_SPLIT_FUSED"] = old


def _assert_split_parity(ref, got):
    for k in ("feature", "bin", "default_left"):
        np.testing.assert_array_equal(np.asarray(ref[k]),
                                      np.asarray(got[k]), err_msg=k)
    # gains come from the identical f32 expression tree — bitwise
    np.testing.assert_array_equal(np.asarray(ref["gain"]),
                                  np.asarray(got["gain"]))
    g_ref = np.asarray(ref["g_tot"])
    h_ref = np.asarray(ref["h_tot"])
    if g_ref.ndim == 2:  # XLA path carries per-feature copies
        g_ref, h_ref = g_ref[:, 0], h_ref[:, 0]
    np.testing.assert_array_equal(g_ref, np.asarray(got["g_tot"]))
    np.testing.assert_array_equal(h_ref, np.asarray(got["h_tot"]))


@pytest.mark.parametrize("highest", [False, True])
@pytest.mark.parametrize("n,c", [(1, 1), (8, 5), (16, 33), (64, 12)])
def test_fused_matches_xla(rng, monkeypatch, n, c, highest):
    if highest:
        monkeypatch.setenv("SHIFU_TPU_HIST_PRECISION", "highest")
    g, h = _hists(rng, n, c)
    fm = jnp.asarray((rng.random(c) > 0.25).astype(np.float32))
    ref = _xla_ref(g, h, fm)
    got = pallas_split.best_splits_pallas(
        g, h, jnp.broadcast_to(fm[None, :], (n, c)),
        float(CFG.reg_lambda), float(CFG.min_instances_per_node),
        interpret=True)
    _assert_split_parity(ref, got)


def test_fused_per_node_masks(rng):
    """(N, C) per-node masks — the lockstep forest's flattened layout —
    must match running the XLA chain with the same 2-D mask."""
    n, c = 12, 9
    g, h = _hists(rng, n, c)
    mask2 = jnp.asarray((rng.random((n, c)) > 0.4).astype(np.float32))
    ref = _xla_ref(g, h, mask2)
    got = pallas_split.best_splits_pallas(
        g, h, mask2, float(CFG.reg_lambda),
        float(CFG.min_instances_per_node), interpret=True)
    _assert_split_parity(ref, got)


def test_tie_break_is_first_flat_index(rng):
    """Duplicated feature columns force exact gain ties; the kernel
    must pick the LOWEST flat feature·(B-1)+bin index — jnp.argmax's
    first-occurrence rule — even when the tie spans column tiles
    (col_tile=2 puts the duplicates in different tiles)."""
    one = rng.normal(size=(4, 1, 16)).astype(np.float32)
    oneh = (np.abs(rng.normal(size=(4, 1, 16))) * 2).astype(np.float32)
    g = jnp.asarray(np.tile(one, (1, 6, 1)))
    h = jnp.asarray(np.tile(oneh, (1, 6, 1)))
    fm = jnp.ones(6, jnp.float32)
    ref = _xla_ref(g, h, fm)
    got = pallas_split.best_splits_pallas(
        g, h, jnp.broadcast_to(fm[None, :], (4, 6)), 1.0, 2.0,
        col_tile=2, interpret=True)
    _assert_split_parity(ref, got)
    assert np.asarray(got["feature"]).max() == 0  # earliest duplicate

def test_all_masked_resolves_to_index_zero(rng):
    """Every gain -inf (all features masked) must yield flat index 0 —
    what jnp.argmax returns on an all-equal row — so downstream
    can_split (isfinite check) sees a well-defined, in-range split."""
    g, h = _hists(rng, 4, 6)
    ref = _xla_ref(g, h, jnp.zeros(6, jnp.float32))
    got = pallas_split.best_splits_pallas(
        g, h, jnp.zeros((4, 6), jnp.float32), 1.0, 2.0, col_tile=2,
        interpret=True)
    _assert_split_parity(ref, got)
    assert np.array_equal(np.asarray(got["feature"]), np.zeros(4))
    assert np.array_equal(np.asarray(got["bin"]), np.zeros(4))
    assert np.all(np.isneginf(np.asarray(got["gain"])))


def test_masked_feature_never_wins(rng):
    """Put an overwhelming gain on a masked feature: the winner must
    come from the unmasked set on both routes."""
    g, h = _hists(rng, 6, 4)
    g = g.at[:, 2, :8].add(100.0)  # feature 2 would dominate
    fm = jnp.asarray([1.0, 1.0, 0.0, 1.0], jnp.float32)
    ref = _xla_ref(g, h, fm)
    got = pallas_split.best_splits_pallas(
        g, h, jnp.broadcast_to(fm[None, :], (6, 4)), 1.0, 2.0,
        interpret=True)
    _assert_split_parity(ref, got)
    assert not np.any(np.asarray(got["feature"]) == 2)


def test_min_instances_masking(rng):
    """A high min-instances floor kills thin splits identically on
    both routes (hessian≈count when hess=1)."""
    cfg = TreeConfig(max_depth=4, n_bins=16, min_instances_per_node=40,
                     min_info_gain=0.0, reg_lambda=1.0,
                     learning_rate=0.1, loss="squared")
    g = jnp.asarray(rng.normal(size=(5, 3, 16)).astype(np.float32))
    h = jnp.asarray(np.abs(rng.normal(size=(5, 3, 16))
                           ).astype(np.float32))  # sums ≪ 40 per side
    fm = jnp.ones(3, jnp.float32)
    ref = _xla_ref(g, h, fm, cfg)
    got = pallas_split.best_splits_pallas(
        g, h, jnp.broadcast_to(fm[None, :], (5, 3)),
        float(cfg.reg_lambda), float(cfg.min_instances_per_node),
        interpret=True)
    _assert_split_parity(ref, got)


def test_split_fused_mode_routing(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_SPLIT_FUSED", "pallas")
    assert pallas_split.split_fused_mode() == "pallas"
    monkeypatch.setenv("SHIFU_TPU_SPLIT_FUSED", "xla")
    assert pallas_split.split_fused_mode() == "xla"
    monkeypatch.setenv("SHIFU_TPU_SPLIT_FUSED", "auto")
    expect = "pallas" if jax.default_backend() == "tpu" else "xla"
    assert pallas_split.split_fused_mode() == expect


def test_build_tree_via_fused_route_matches_xla(rng, monkeypatch):
    """End-to-end: a whole build_tree through SHIFU_TPU_SPLIT_FUSED=
    pallas (interpret on CPU) grows the identical tree. Caches are
    cleared between routes — the knob is read at trace time, so a
    stale jit entry would silently reuse the other route."""
    bins = rng.integers(0, 15, size=(1500, 6)).astype(np.int32)
    y = (bins[:, 0] >= 7).astype(np.float32)
    cfg = TreeConfig(max_depth=3, n_bins=16)
    args = (jnp.asarray(bins.T), jnp.asarray(-y),
            jnp.asarray(np.ones_like(y)), jnp.ones(6, jnp.float32))
    monkeypatch.setenv("SHIFU_TPU_SPLIT_FUSED", "xla")
    jax.clear_caches()
    ref = gbdt.build_tree(cfg, *args)
    monkeypatch.setenv("SHIFU_TPU_SPLIT_FUSED", "pallas")
    jax.clear_caches()
    got = gbdt.build_tree(cfg, *args)
    jax.clear_caches()  # don't leak pallas-route traces to other tests
    for k in ref:
        np.testing.assert_array_equal(np.asarray(ref[k]),
                                      np.asarray(got[k]), err_msg=k)
