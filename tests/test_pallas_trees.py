"""Fused Pallas tree-ensemble inference (ops/pallas_trees.py) parity.

The interpretive `bin_dataset + predict_trees` walk (`gbdt.predict`'s
"xla" route) is the pinned reference; the fused kernel must reproduce
it — in-register binning, missing-value `default_left` routing,
categorical cat_map routing, and the `gbdt.predict` convert (RF mean;
GBT lr·sum with the ±30-clip sigmoid) — through interpret mode on CPU.
Per-row ROUTING is integer-exact, so structure decisions bit-match;
final scores may differ at f32-ulp scale only (the kernel accumulates
the leaf sum tree-by-tree where numpy pairwise-reassociates, and
jnp.exp vs np.exp in the sigmoid).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from shifu_tpu.models import gbdt
from shifu_tpu.models.gbdt import TreeConfig
from shifu_tpu.ops import pallas_trees


def _dataset(rng, n=600, cn=5, cc=2, vocab=6, n_bins=16, miss=0.08):
    """Raw cleaned blocks (NaN-missing numeric + coded categoricals)
    with their binning tables — the layout `gbdt.predict` serves."""
    dense = rng.normal(0, 1, (n, cn)).astype(np.float32)
    dense[rng.random((n, cn)) < miss] = np.nan
    codes = rng.integers(0, vocab, (n, cc)).astype(np.int32)
    codes[rng.random((n, cc)) < miss] = -1  # missing category
    qs = np.linspace(0, 1, n_bins)[1:-1]
    num_cuts = np.nanquantile(dense, qs, axis=0).astype(np.float32)
    tables = gbdt.make_bin_tables(
        num_cuts, [rng.permutation(vocab).astype(np.int32)
                   for _ in range(cc)], n_bins)
    y = ((np.nan_to_num(dense[:, 0]) + 0.4 * codes[:, 0]) > 0.5) \
        .astype(np.float32)
    return dense, codes, tables, y


def _spec(kind, cfg, trees, tables):
    meta = {"kind": kind,
            "treeConfig": {"max_depth": cfg.max_depth,
                           "n_bins": cfg.n_bins,
                           "learning_rate": cfg.learning_rate,
                           "loss": cfg.loss}}
    import jax
    params = {"trees": jax.tree.map(np.asarray, trees),
              "tables": tables}
    return meta, params


def _both_routes(meta, params, dense, codes):
    ref = gbdt.predict(meta, params, dense, codes, route="xla")
    fused = gbdt.predict(meta, params, dense, codes, route="pallas")
    return ref, fused


@pytest.mark.parametrize("loss", ["squared", "log"])
def test_fused_matches_walk_gbt(rng, loss):
    """Trained GBT, mixed numeric/categorical with missing on both:
    fused route ≡ the interpretive walk at ulp tolerance."""
    n_bins = 16
    dense, codes, tables, y = _dataset(rng, n_bins=n_bins)
    bins = gbdt.bin_dataset(tables, dense, codes, n_bins)
    cfg = TreeConfig(max_depth=4, n_bins=n_bins, learning_rate=0.2,
                     loss=loss)
    trees, _ = gbdt.build_gbt(cfg, bins, y, np.ones_like(y), 5)
    meta, params = _spec("gbt", cfg, trees, tables)
    ref, fused = _both_routes(meta, params, dense, codes)
    np.testing.assert_allclose(fused, ref, rtol=1e-6, atol=1e-7)


def test_fused_matches_walk_rf(rng):
    """RF (in-kernel mean convert) over its Poisson-bagged forest."""
    n_bins = 16
    dense, codes, tables, y = _dataset(rng, n_bins=n_bins)
    bins = gbdt.bin_dataset(tables, dense, codes, n_bins)
    cfg = TreeConfig(max_depth=3, n_bins=n_bins)
    trees = gbdt.build_rf(cfg, bins, y, np.ones_like(y), 4, "SQRT",
                          1.0, 7)
    meta, params = _spec("rf", cfg, trees, tables)
    ref, fused = _both_routes(meta, params, dense, codes)
    np.testing.assert_allclose(fused, ref, rtol=1e-6, atol=1e-7)


def _hand_tree(n_nodes, feature, bin_, default_left, leaves):
    """One depth-1 tree: root split on `feature` at `bin_`, children
    leaves. Arrays in the (T, n_nodes) stacked-tree layout."""
    t = {"feature": np.full((1, n_nodes), -1, np.int32),
         "bin": np.zeros((1, n_nodes), np.int32),
         "default_left": np.zeros((1, n_nodes), np.int32),
         "is_leaf": np.ones((1, n_nodes), bool),
         "gain": np.zeros((1, n_nodes), np.float32),
         "leaf_value": np.zeros((1, n_nodes), np.float32)}
    t["feature"][0, 0] = feature
    t["bin"][0, 0] = bin_
    t["default_left"][0, 0] = default_left
    t["is_leaf"][0, 0] = False
    t["leaf_value"][0, 1] = leaves[0]
    t["leaf_value"][0, 2] = leaves[1]
    return t


@pytest.mark.parametrize("default_left", [0, 1])
def test_missing_routes_by_default_left(default_left):
    """NaN rows must take the split's default direction — both ways —
    and land on the same leaf as the reference walk."""
    n_bins = 8
    cfg = TreeConfig(max_depth=1, n_bins=n_bins, learning_rate=1.0,
                     loss="squared")
    trees = _hand_tree(cfg.n_nodes, feature=0, bin_=2,
                       default_left=default_left, leaves=(-1.0, 2.0))
    num_cuts = np.arange(1, n_bins - 1, dtype=np.float32)[:, None]
    tables = gbdt.make_bin_tables(num_cuts, [], n_bins)
    dense = np.array([[0.5], [2.5], [np.nan], [5.5]], np.float32)
    meta, params = _spec("gbt", cfg, trees, tables)
    ref, fused = _both_routes(meta, params, dense, None)
    np.testing.assert_array_equal(fused, ref)
    # the NaN row went where default_left says, not where a bin would
    assert fused[2] == (-1.0 if default_left else 2.0)


def test_categorical_cat_map_routing(rng):
    """Categorical columns route through the posRate-ordered cat_map
    (identity cuts host-mapped by make_fused_inputs) — including -1
    and out-of-vocab missing codes."""
    n_bins, vocab = 8, 4
    cfg = TreeConfig(max_depth=1, n_bins=n_bins, learning_rate=1.0,
                     loss="squared")
    trees = _hand_tree(cfg.n_nodes, feature=0, bin_=1,
                       default_left=0, leaves=(3.0, -4.0))
    order = np.array([2, 0, 3, 1], np.int32)  # raw code → ordered bin
    tables = gbdt.make_bin_tables(np.zeros((n_bins - 2, 0), np.float32),
                                  [order], n_bins)
    codes = np.array([[0], [1], [2], [3], [-1], [vocab]], np.int32)
    dense = np.zeros((len(codes), 0), np.float32)
    meta, params = _spec("gbt", cfg, trees, tables)
    ref, fused = _both_routes(meta, params, dense, codes)
    np.testing.assert_array_equal(fused, ref)
    expect = np.where(order <= 1, 3.0, -4.0).astype(np.float32)
    np.testing.assert_array_equal(fused[:vocab], expect)
    # missing codes (-1 and vocab-length) take default_left=0 → right
    np.testing.assert_array_equal(fused[vocab:], [-4.0, -4.0])


def test_logloss_clip_boundary():
    """Raw scores past ±30 clip BEFORE the sigmoid on both routes —
    the exact `gbdt.predict` convert, saturating to {σ(-30), σ(30)}."""
    n_bins = 8
    cfg = TreeConfig(max_depth=1, n_bins=n_bins, learning_rate=1.0,
                     loss="log")
    trees = _hand_tree(cfg.n_nodes, feature=0, bin_=2, default_left=0,
                       leaves=(-100.0, 100.0))
    num_cuts = np.arange(1, n_bins - 1, dtype=np.float32)[:, None]
    tables = gbdt.make_bin_tables(num_cuts, [], n_bins)
    dense = np.array([[0.5], [5.5]], np.float32)
    meta, params = _spec("gbt", cfg, trees, tables)
    ref, fused = _both_routes(meta, params, dense, None)
    np.testing.assert_allclose(fused, ref, rtol=1e-6, atol=0)
    np.testing.assert_allclose(
        fused, [1.0 / (1.0 + np.exp(30.0)),
                1.0 / (1.0 + np.exp(-30.0))], rtol=1e-6)


def test_stub_tree_all_leaf():
    """A root-leaf-only ensemble (max_depth 0 fold: every node a leaf)
    must score the constant on both routes — the walk never moves."""
    n_bins = 8
    cfg = TreeConfig(max_depth=2, n_bins=n_bins, learning_rate=0.5,
                     loss="squared")
    t = {"feature": np.full((2, cfg.n_nodes), -1, np.int32),
         "bin": np.zeros((2, cfg.n_nodes), np.int32),
         "default_left": np.zeros((2, cfg.n_nodes), np.int32),
         "is_leaf": np.ones((2, cfg.n_nodes), bool),
         "gain": np.zeros((2, cfg.n_nodes), np.float32),
         "leaf_value": np.zeros((2, cfg.n_nodes), np.float32)}
    t["leaf_value"][0, 0] = 1.5
    t["leaf_value"][1, 0] = -0.5
    num_cuts = np.arange(1, n_bins - 1, dtype=np.float32)[:, None]
    tables = gbdt.make_bin_tables(num_cuts, [], n_bins)
    dense = np.array([[0.1], [np.nan], [9.0]], np.float32)
    meta, params = _spec("gbt", cfg, t, tables)
    ref, fused = _both_routes(meta, params, dense, None)
    np.testing.assert_array_equal(fused, ref)
    np.testing.assert_allclose(fused, np.full(3, 0.5, np.float32),
                               rtol=1e-6)


def test_route_knob_and_explicit_override(rng, monkeypatch):
    """SHIFU_TPU_TREE_FUSED resolves the default route (auto → xla off
    TPU); an explicit route= argument overrides the knob either way."""
    import jax
    monkeypatch.setenv("SHIFU_TPU_TREE_FUSED", "auto")
    expect_auto = "pallas" if jax.default_backend() == "tpu" else "xla"
    assert pallas_trees.tree_fused_mode() == expect_auto
    monkeypatch.setenv("SHIFU_TPU_TREE_FUSED", "pallas")
    assert pallas_trees.tree_fused_mode() == "pallas"
    monkeypatch.setenv("SHIFU_TPU_TREE_FUSED", "xla")
    assert pallas_trees.tree_fused_mode() == "xla"

    n_bins = 16
    dense, codes, tables, y = _dataset(rng, n=200, n_bins=n_bins)
    bins = gbdt.bin_dataset(tables, dense, codes, n_bins)
    cfg = TreeConfig(max_depth=3, n_bins=n_bins)
    trees, _ = gbdt.build_gbt(cfg, bins, y, np.ones_like(y), 3)
    meta, params = _spec("gbt", cfg, trees, tables)
    # env pins xla; the explicit pallas route must still run fused
    fused = gbdt.predict(meta, params, dense, codes, route="pallas")
    default = gbdt.predict(meta, params, dense, codes)
    np.testing.assert_allclose(fused, default, rtol=1e-6, atol=1e-7)


def test_padding_and_row_tile_invariance(rng):
    """Scores are invariant to bucket padding (serving repeats the
    last row up to the bucket) and to the kernel row tile — each row
    only ever sees its own lane."""
    n_bins = 16
    dense, codes, tables, y = _dataset(rng, n=150, n_bins=n_bins)
    bins = gbdt.bin_dataset(tables, dense, codes, n_bins)
    cfg = TreeConfig(max_depth=3, n_bins=n_bins)
    trees, _ = gbdt.build_gbt(cfg, bins, y, np.ones_like(y), 3)
    meta, params = _spec("gbt", cfg, trees, tables)
    base = gbdt.predict(meta, params, dense, codes, route="pallas")
    pad = 256 - len(dense)
    padded = gbdt.predict(
        meta, params,
        np.concatenate([dense, np.repeat(dense[-1:], pad, 0)]),
        np.concatenate([codes, np.repeat(codes[-1:], pad, 0)]),
        route="pallas")
    np.testing.assert_array_equal(padded[:len(dense)], base)

    fb = gbdt.make_fused_inputs(tables, dense, codes, n_bins)
    import jax
    trees_np = jax.tree.map(np.asarray, params["trees"])
    packed, _ = pallas_trees.pack_ensemble(trees_np)
    kw = dict(n_trees=3, kind="gbt", loss=cfg.loss,
              learning_rate=cfg.learning_rate, max_depth=cfg.max_depth,
              n_bins=n_bins, interpret=jax.default_backend() != "tpu")
    t128 = pallas_trees.predict_ensemble(
        jnp.asarray(packed), jnp.asarray(fb.valuesT),
        jnp.asarray(fb.cuts), row_tile=128, **kw)
    t512 = pallas_trees.predict_ensemble(
        jnp.asarray(packed), jnp.asarray(fb.valuesT),
        jnp.asarray(fb.cuts), row_tile=512, **kw)
    np.testing.assert_array_equal(np.asarray(t128), np.asarray(t512))
