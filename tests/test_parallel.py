"""SPMD-by-default tests: the REAL pipeline over the 8-device mesh.

The reference certifies its distributed loop with GuaguaMRUnitDriver
(whole master–worker app in one JVM, SURVEY.md §4.3); here the analog
is the real processors running over the 8-virtual-device CPU mesh and
matching their 1-device results — plus an HLO check that the GBDT
histogram reduction is an all-reduce (psum), not an all-gather of the
row-sharded bin matrix (dt/DTMaster.java:276 aggregation semantics).
"""

import json
import os

import numpy as np
import pytest


def _train_and_collect(root):
    from shifu_tpu.processor import (init as init_proc, norm as norm_proc,
                                     stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext
    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    from shifu_tpu.models.spec import load_model
    _, meta, params = load_model(ctx.path_finder.model_path(0, "nn"))
    with open(ctx.path_finder.val_error_path()) as f:
        val = json.load(f)
    return params, val, ctx


def test_train_mesh_parity_8dev_vs_1dev(tmp_path, rng):
    """`shifu train` over the 8-device mesh produces the same model as
    1-device within fp tolerance (VERDICT #1 done-when)."""
    import jax
    from tests.synth import make_model_set
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"

    # identical data for both runs: fresh identically-seeded rngs (the
    # session `rng` fixture has been advanced by earlier tests)
    params8, val8, ctx8 = _train_and_collect(
        make_model_set(tmp_path / "m8", np.random.default_rng(777),
                       n_rows=1500))
    try:
        os.environ["SHIFU_TPU_MESH_DEVICES"] = "1"
        params1, val1, ctx1 = _train_and_collect(
            make_model_set(tmp_path / "m1",
                           np.random.default_rng(777), n_rows=1500))
    finally:
        os.environ.pop("SHIFU_TPU_MESH_DEVICES", None)

    # same data (same rng seed), same seeds → same model up to collective
    # reduction order
    for l8, l1 in zip(params8, params1):
        for k in l8:
            np.testing.assert_allclose(np.asarray(l8[k]), np.asarray(l1[k]),
                                       rtol=2e-3, atol=2e-4)
    assert abs(val8["bestValError"][0] - val1["bestValError"][0]) < 1e-3


def test_stats_mesh_pad_correction(tmp_path, rng):
    """Stats over the 8-device mesh with a row count NOT divisible by 8:
    missing counts and bin counts must not absorb the padding rows."""
    from tests.synth import make_model_set
    from shifu_tpu.processor import init as init_proc, stats as stats_proc
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=1003)  # 1003 % 8 != 0
    for proc in (init_proc, stats_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    ctx = ProcessorContext.load(root)
    total_rows = None
    for cc in ctx.column_configs:
        if not cc.is_candidate or cc.columnBinning.binCountPos is None:
            continue
        st = cc.columnStats
        bn = cc.columnBinning
        n = int(np.sum(bn.binCountPos) + np.sum(bn.binCountNeg))
        # every row lands in exactly one bin (incl. missing): counts sum
        # to the real row count, not the padded one
        assert n == st.totalCount, (cc.columnName, n, st.totalCount)
        assert st.missingCount >= 0
        total_rows = st.totalCount
    assert total_rows is not None and total_rows <= 1003


def test_gbdt_sharded_histogram_matches_single_device():
    """A tree built on the 8-device mesh with row-sharded bins picks
    the same splits as single-device (VERDICT #5) — up to near-tie
    flips: an 8-way psum and a serial sum round differently in f32, so
    a gain tie at that precision can legitimately resolve either way
    on BOTH histogram paths (sibling subtraction widens the window via
    parent − left cancellation). The contract asserted here: at most a
    couple of flipped decisions, agreeing predictions, identical
    histograms where splits agree."""
    import jax
    import jax.numpy as jnp
    from shifu_tpu.models import gbdt

    # dedicated generator: the session rng's position varies with test
    # order, and this test's tolerance accounting needs fixed data
    rng = np.random.default_rng(424242)
    r, c, b = 1000, 6, 16
    bins = rng.integers(0, b - 1, (r, c)).astype(np.int32)
    y = (rng.random(r) < 0.4).astype(np.float32)
    w = np.ones(r, np.float32)
    cfg = gbdt.TreeConfig(max_depth=4, n_bins=b, loss="log")

    def compare(subtract_env, max_flips):
        try:
            os.environ["SHIFU_TPU_HIST_SUBTRACT"] = subtract_env
            trees8, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=5)
            os.environ["SHIFU_TPU_MESH_DEVICES"] = "1"
            trees1, _ = gbdt.build_gbt(cfg, bins, y, w, n_trees=5)
        finally:
            os.environ.pop("SHIFU_TPU_MESH_DEVICES", None)
            os.environ.pop("SHIFU_TPU_HIST_SUBTRACT", None)
        flips = int(
            (np.asarray(trees8["bin"]) != np.asarray(trees1["bin"])).sum()
            + (np.asarray(trees8["feature"]) !=
               np.asarray(trees1["feature"])).sum())
        assert flips <= max_flips,             f"{flips} split decisions flipped (subtract={subtract_env})"
        binsT = jnp.asarray(bins.T)
        p8 = np.asarray(gbdt.predict_trees(
            jax.tree.map(jnp.asarray, trees8), binsT, cfg.max_depth,
            cfg.n_bins)).sum(axis=0)
        p1 = np.asarray(gbdt.predict_trees(
            jax.tree.map(jnp.asarray, trees1), binsT, cfg.max_depth,
            cfg.n_bins)).sum(axis=0)
        np.testing.assert_allclose(p8, p1, rtol=0.05, atol=0.02)
        return flips

    compare("0", max_flips=2)   # direct path: ulp-level ties only
    compare("1", max_flips=5)   # subtraction widens the tie window


def test_rf_sharded_matches_single_device(rng):
    """build_rf over the 8-device mesh grows the SAME forest (splits,
    leaves) as 1-device — VERDICT r2 #3: RF correctness under SPMD."""
    from shifu_tpu.models import gbdt

    r, c, b = 1000, 6, 16
    bins = rng.integers(0, b - 1, (r, c)).astype(np.int32)
    y = (rng.random(r) < 0.4).astype(np.float32)
    w = np.ones(r, np.float32)
    cfg = gbdt.TreeConfig(max_depth=4, n_bins=b)

    trees8 = gbdt.build_rf(cfg, bins, y, w, n_trees=4,
                           subset_strategy="ALL", bagging_rate=1.0, seed=42)
    try:
        os.environ["SHIFU_TPU_MESH_DEVICES"] = "1"
        trees1 = gbdt.build_rf(cfg, bins, y, w, n_trees=4,
                               subset_strategy="ALL", bagging_rate=1.0,
                               seed=42)
    finally:
        os.environ.pop("SHIFU_TPU_MESH_DEVICES", None)

    np.testing.assert_array_equal(trees8["feature"], trees1["feature"])
    np.testing.assert_array_equal(trees8["bin"], trees1["bin"])
    np.testing.assert_allclose(trees8["leaf_value"], trees1["leaf_value"],
                               rtol=1e-4, atol=1e-5)


def test_forest_histogram_reduction_is_psum_not_gather(rng):
    """HLO check for the lockstep forest histogram: all-reduce (psum),
    never an all-gather of the row-sharded bins — the RF analog of the
    GBT assertion below."""
    import jax
    from shifu_tpu.models.gbdt import _forest_level_histograms
    from shifu_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.default_mesh()
    assert mesh.shape["data"] == 8

    r, c, b, s, t = 1024, 4, 8, 4, 3
    binsT = mesh_mod.shard_axis(
        mesh, np.ascontiguousarray(
            rng.integers(0, b, (r, c)).astype(np.int32).T), 1)
    node = mesh_mod.shard_axis(
        mesh, rng.integers(0, s, (t, r)).astype(np.int32), 1)
    grad = mesh_mod.shard_axis(
        mesh, rng.normal(0, 1, (t, r)).astype(np.float32), 1)
    hess = mesh_mod.shard_axis(mesh, np.ones((t, r), np.float32), 1)

    def hist(binsT, node, grad, hess):
        return _forest_level_histograms(binsT, node, grad, hess, 0, s, b,
                                        mesh=mesh)

    hlo = jax.jit(hist).lower(binsT, node, grad, hess).compile().as_text()
    assert "all-reduce" in hlo, "forest histogram should reduce via psum"
    assert "all-gather" not in hlo, \
        "row-sharded operands must not be all-gathered"

    # numerics: matches a per-tree host loop
    g, _ = jax.jit(hist)(binsT, node, grad, hess)
    bins_h = np.asarray(binsT).T
    node_h = np.asarray(node)
    grad_h = np.asarray(grad)
    g_ref = np.zeros((t, s, c, b), np.float32)
    for ti in range(t):
        for i in range(r):
            if node_h[ti, i] < s:
                for j in range(c):
                    g_ref[ti, node_h[ti, i], j, bins_h[i, j]] += grad_h[ti, i]
    np.testing.assert_allclose(np.asarray(g), g_ref, rtol=1e-5, atol=1e-4)


def test_gbdt_histogram_reduction_is_psum_not_gather(rng):
    """HLO check: the sharded level-histogram reduces with all-reduce
    (psum) and never all-gathers the row-sharded (R, C) bin matrix —
    the silent-gather failure mode VERDICT #5 warns about."""
    import jax
    import jax.numpy as jnp
    from shifu_tpu.models.gbdt import _level_histograms
    from shifu_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.default_mesh()
    assert mesh.shape["data"] == 8

    r, c, b, s = 1024, 4, 8, 4
    binsT = mesh_mod.shard_axis(
        mesh, np.ascontiguousarray(rng.integers(0, b, (r, c)).astype(np.int32).T), 1)
    node = mesh_mod.shard_axis(mesh, rng.integers(0, s, r).astype(np.int32), 0)
    grad = mesh_mod.shard_axis(mesh, rng.normal(0, 1, r).astype(np.float32), 0)
    hess = mesh_mod.shard_axis(mesh, np.ones(r, np.float32), 0)

    def hist(binsT, node, grad, hess):
        return _level_histograms(binsT, node, grad, hess, 0, s, b, mesh=mesh)

    lowered = jax.jit(hist).lower(binsT, node, grad, hess)
    hlo = lowered.compile().as_text()
    assert "all-reduce" in hlo, "histogram reduction should be a psum"
    assert "all-gather" not in hlo, \
        "row-sharded operands must not be all-gathered"

    # and the result matches the unsharded computation
    g, h = jax.jit(hist)(binsT, node, grad, hess)
    bins_h = np.asarray(binsT).T
    node_h = np.asarray(node)
    grad_h = np.asarray(grad)
    g_ref = np.zeros((s, c, b), np.float32)
    for i in range(r):
        if node_h[i] < s:
            for j in range(c):
                g_ref[node_h[i], j, bins_h[i, j]] += grad_h[i]
    np.testing.assert_allclose(np.asarray(g), g_ref, rtol=1e-5, atol=1e-4)


def _run_family_pipeline(root, algorithm):
    from shifu_tpu.processor import init as init_proc
    from shifu_tpu.processor import norm as norm_proc
    from shifu_tpu.processor import stats as stats_proc
    from shifu_tpu.processor import train as train_proc
    from shifu_tpu.processor.base import ProcessorContext
    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    return ctx


@pytest.mark.parametrize("algorithm,kind,norm_type,params,epochs,tol", [
    ("WDL", "wdl", "ZSCALE_INDEX",
     {"NumHiddenNodes": [8], "ActivationFunc": ["relu"], "EmbedSize": 4,
      "LearningRate": 0.05}, None, (2e-3, 2e-4)),
    # MTL runs a PINNED short horizon with a TIGHT tolerance. At the
    # synth default of 40 epochs the two meshes diverge chaotically
    # (measured leaf deltas: 1e-10 @ 1 epoch, 0 @ 2, 6e-8 @ 8,
    # ~1e-4 @ 32, ~0.15 @ 40 — pure float-order amplification through
    # the epoch scan plus a best-val-epoch selection flip, NOT a
    # model-axis semantics bug: the 'model'-sharded head psum sums
    # partial products in a different order than the replicated
    # matmul). 8 epochs is past several optimizer steps on every
    # shard yet before chaos outruns float32, so a REAL regression in
    # the head-sharding math (wrong psum, dropped shard, stale
    # replicated trunk) fails loudly while benign reduction-order
    # noise stays ~4 orders of magnitude under the gate.
    ("MTL", "mtl", "ZSCALE",
     {"NumHiddenNodes": [8], "ActivationFunc": ["relu"],
      "LearningRate": 0.05}, 8, (1e-4, 1e-5)),
])
def test_model_axis_parity(tmp_path, monkeypatch, algorithm, kind,
                           norm_type, params, epochs, tol):
    """SHIFU_TPU_MESH_MODEL=2 (data=4 × model=2 mesh; WDL embedding /
    MTL head rows sharded over 'model') trains the same model as the
    pure data mesh — the product model-parallel path (VERDICT r3 next
    #10), not a toy dryrun step."""
    import json as json_mod

    import jax
    from tests.synth import make_model_set
    from shifu_tpu.models.spec import load_model
    assert len(jax.devices()) == 8

    def build(sub):
        root = make_model_set(tmp_path / sub, np.random.default_rng(4242),
                              n_rows=1200, algorithm=algorithm,
                              norm_type=norm_type,
                              train_params=dict(params))
        mcp = os.path.join(root, "ModelConfig.json")
        mc = json_mod.load(open(mcp))
        if algorithm == "MTL":
            mc["dataSet"]["targetColumnName"] = "diagnosis|diagnosis"
        if epochs is not None:
            mc["train"]["numTrainEpochs"] = epochs
        json_mod.dump(mc, open(mcp, "w"))
        return root

    monkeypatch.delenv("SHIFU_TPU_MESH_MODEL", raising=False)
    ctx_d = _run_family_pipeline(build("data_only"), algorithm)
    monkeypatch.setenv("SHIFU_TPU_MESH_MODEL", "2")
    ctx_m = _run_family_pipeline(build("model_axis"), algorithm)

    _, _, p_d = load_model(ctx_d.path_finder.model_path(0, kind))
    _, _, p_m = load_model(ctx_m.path_finder.model_path(0, kind))
    flat_d = jax.tree.leaves(p_d)
    flat_m = jax.tree.leaves(p_m)
    assert len(flat_d) == len(flat_m)
    rtol, atol = tol
    for a, b in zip(flat_d, flat_m):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------
# elastic mesh: logical axis rules, 2-D DCN×ICI builder, spec re-resolution
# ---------------------------------------------------------------------------

def test_mesh_rules_defaults_and_env_overrides(monkeypatch):
    from shifu_tpu.parallel import mesh as mesh_mod
    rules = mesh_mod.default_rules()
    assert rules("rows", "hidden") == ("data", "model")
    assert rules("unknown") == (None,)
    # override: replicate 'hidden' (empty RHS), re-point 'cat' to data
    monkeypatch.setenv("SHIFU_TPU_MESH_RULES", "hidden=,cat=data")
    rules = mesh_mod.default_rules()
    assert rules("hidden") == (None,)
    assert rules("cat") == ("data",)
    assert rules("task") == ("model",)   # untouched default
    monkeypatch.setenv("SHIFU_TPU_MESH_RULES", "garbage")
    with pytest.raises(ValueError, match="SHIFU_TPU_MESH_RULES"):
        mesh_mod.default_rules()


def test_mesh_rules_never_duplicate_a_physical_axis():
    """jax rejects P('model','model'); when two logical dims map to the
    same physical axis the FIRST claim wins and later ones replicate
    (MTL heads: task and hidden both default to 'model')."""
    from jax.sharding import PartitionSpec as P

    from shifu_tpu.parallel import mesh as mesh_mod
    rules = mesh_mod.default_rules()
    assert rules.spec("task", "hidden") == P("model", None)
    assert rules.spec("hidden", "task") == P("model", None)


def test_make_mesh_multihost_host_major_and_ici_validation():
    """Multi-host device ordering is host-major so each model group
    stays within one host (ICI); an n_model that cannot divide a
    host's local device count must fail loudly, naming the knob."""
    from types import SimpleNamespace

    from shifu_tpu.parallel import mesh as mesh_mod

    def fake(host, i):
        return SimpleNamespace(process_index=host, id=host * 10 + i)

    # 2 hosts × 4 local: n_model=2 keeps each model pair on one host
    devs = [fake(h, i) for h in (1, 0) for i in range(4)]   # shuffled
    try:
        mesh_mod.make_mesh(4, 2, devices=devs)
    except TypeError:
        # Mesh() itself rejects the fakes on some jax versions — the
        # ordering/validation code above it is what this test covers
        pass
    # n_model=8 spans hosts → ValueError naming the knob
    with pytest.raises(ValueError, match="SHIFU_TPU_MESH_MODEL"):
        mesh_mod.make_mesh(1, 8, devices=devs)
    # uneven per-host counts are rejected too
    devs_uneven = [fake(0, i) for i in range(6)] + [fake(1, i)
                                                    for i in range(2)]
    with pytest.raises(ValueError, match="local device count"):
        mesh_mod.make_mesh(2, 4, devices=devs_uneven)


def test_resolve_spec_against_foreign_meshes():
    import jax

    from shifu_tpu.parallel import mesh as mesh_mod
    mesh = mesh_mod.make_mesh(4, 2)
    # recorded on a matching mesh: names survive
    spec = mesh_mod.resolve_spec(mesh, ["data", "model"], (8, 6))
    assert tuple(spec) == ("data", "model")
    # dim not divisible by the axis → that dim replicates
    spec = mesh_mod.resolve_spec(mesh, [None, "model"], (8, 5))
    assert tuple(spec) == ()
    # axis name this mesh does not have → replicates
    spec = mesh_mod.resolve_spec(mesh, ["expert"], (8,))
    assert tuple(spec) == ()
    # 1-device mesh: everything replicates trivially but specs survive
    one = mesh_mod.make_mesh(1, 1, devices=jax.devices()[:1])
    spec = mesh_mod.resolve_spec(one, ["data", "model"], (8, 6))
    assert tuple(spec) == ("data", "model")


def test_mesh_topology_record():
    from shifu_tpu.parallel import mesh as mesh_mod
    top = mesh_mod.mesh_topology(mesh_mod.make_mesh(4, 2))
    assert top == {"axes": ["data", "model"], "shape": [4, 2],
                   "devices": 8, "hosts": 1}


def test_leased_devices_follow_slice_env(monkeypatch):
    """The device-slice lease seam: SHIFU_TPU_DEVICE_SLICE filters the
    devices default_mesh builds over; a partial id match refuses loudly
    (never a silent shrink onto chips another node leased); a fully
    renumbered visible set no larger than the lease passes through
    (TPU_VISIBLE_DEVICES already did the narrowing)."""
    import jax

    from shifu_tpu.parallel import mesh as mesh_mod
    monkeypatch.delenv("SHIFU_TPU_MESH_DEVICES", raising=False)
    monkeypatch.setenv("SHIFU_TPU_DEVICE_SLICE", "2,5")
    devs = mesh_mod.leased_devices()
    assert sorted(d.id for d in devs) == [2, 5]
    m = mesh_mod.default_mesh()
    assert m.devices.size == 2
    assert sorted(d.id for d in m.devices.flat) == [2, 5]
    assert len(mesh_mod.leased_local_devices()) == 2
    # partial match: id 2 resolves, 99 does not → refuse
    monkeypatch.setenv("SHIFU_TPU_DEVICE_SLICE", "2,99")
    with pytest.raises(RuntimeError, match="refusing"):
        mesh_mod.leased_devices()
    # renumbered visibility: nothing matches but the visible set is no
    # larger than the lease — visibility narrowing already happened
    monkeypatch.setenv("SHIFU_TPU_DEVICE_SLICE", "98,99")
    got = mesh_mod.leased_devices(jax.devices()[:2])
    assert [d.id for d in got] == [0, 1]
    # malformed slice env names the knob
    monkeypatch.setenv("SHIFU_TPU_DEVICE_SLICE", "2,x")
    with pytest.raises(ValueError, match="SHIFU_TPU_DEVICE_SLICE"):
        mesh_mod.leased_devices()
    # no slice env → the whole set, untouched
    monkeypatch.delenv("SHIFU_TPU_DEVICE_SLICE")
    assert len(mesh_mod.leased_devices()) == len(jax.devices())
