"""Pipeline DAG scheduler (tier-1): scheduling semantics on synthetic
graphs, and the end-to-end contract on a real model set — outputs of a
DAG run are bitwise identical to the same nodes walked sequentially,
RESUME parks completed nodes as ``cached``, and a failure poisons only
the failing node's descendants while every independent branch runs.
"""

import hashlib
import json
import os
import shutil
import threading
import time

import pytest

from shifu_tpu import profiling, resilience
from shifu_tpu.pipeline.nodes import pipeline_nodes, variant_dir
from shifu_tpu.pipeline.scheduler import (CACHED, DONE, FAILED, POISONED,
                                          DagError, Node, run_dag)


def _states(report):
    return {r["node"]: r["state"] for r in report["nodes"]}


# ---------------------------------------------------------------------------
# scheduler semantics (synthetic graphs, no subprocesses)
# ---------------------------------------------------------------------------

def test_dag_failure_poisons_descendants_only(tmp_path):
    """b fails → its descendant c is poisoned; the independent d/e
    branch still runs; DagError carries the full report and the abort
    marker names the failing node (dist.py discipline)."""
    ran = []

    def ok(name):
        return lambda: ran.append(name)

    def boom():
        raise OSError("synthetic")

    nodes = [
        Node("a", ok("a")),
        Node("b", boom, deps=("a",)),
        Node("c", ok("c"), deps=("b",)),
        Node("d", ok("d"), deps=("a",)),
        Node("e", ok("e")),
    ]
    with pytest.raises(DagError) as ei:
        run_dag(nodes, workers=2, root=str(tmp_path), label="t")
    rep = ei.value.report
    assert _states(rep) == {"a": DONE, "b": FAILED, "c": POISONED,
                            "d": DONE, "e": DONE}
    assert sorted(ran) == ["a", "d", "e"]
    assert rep["failed"] == "b"
    assert "'c'" in str(ei.value) and "all other" in str(ei.value)
    marker = resilience.check_abort()
    assert marker is not None and marker["site"] == "dag.b"
    resilience.clear_abort()
    resilience.set_abort_scope(None)


def test_dag_report_schema_and_cached(tmp_path):
    """Per-node records carry exactly profiling.DAG_FIELDS; a true
    done_check parks the node as cached without calling fn; the summary
    block carries exactly DAG_SUMMARY_FIELDS."""
    calls = []
    nodes = [
        Node("a", lambda: calls.append("a")),
        Node("b", lambda: calls.append("b"), deps=("a",),
             done_check=lambda: True),
        Node("c", lambda: calls.append("c"), deps=("b",)),
    ]
    rep = run_dag(nodes, workers=2)
    assert _states(rep) == {"a": DONE, "b": CACHED, "c": DONE}
    assert calls == ["a", "c"]
    assert tuple(rep) == profiling.DAG_SUMMARY_FIELDS
    for rec in rep["nodes"]:
        assert tuple(rec) == profiling.DAG_FIELDS
    # critical path covers the chain through real (non-cached) work
    chain = [r["node"] for r in rep["nodes"] if r["critical_path"]]
    assert "a" in chain or "c" in chain
    assert rep["failed"] is None


def test_dag_validation_rejects_bad_graphs():
    with pytest.raises(ValueError, match="duplicate"):
        run_dag([Node("a", lambda: None), Node("a", lambda: None)])
    with pytest.raises(ValueError, match="unknown node"):
        run_dag([Node("a", lambda: None, deps=("ghost",))])
    with pytest.raises(ValueError, match="cycle"):
        run_dag([Node("a", lambda: None, deps=("b",)),
                 Node("b", lambda: None, deps=("a",))])


def test_dag_host_nodes_bypass_device_worker_cap():
    """With a single device slot occupied by a running trainer, a
    host-only node must still be admitted (it unblocks the trainer
    here; if host nodes queued behind the device cap this would time
    out)."""
    release = threading.Event()

    def device_fn():
        assert release.wait(timeout=30), \
            "host-only node queued behind device worker cap"

    nodes = [
        Node("trainer", device_fn, device=True),
        Node("host", release.set, device=False),
    ]
    rep = run_dag(nodes, workers=1)
    assert _states(rep) == {"trainer": DONE, "host": DONE}


def test_dag_device_cap_is_respected():
    """SHIFU_TPU_DAG_WORKERS=1 → two device nodes never overlap."""
    active, peak = [0], [0]
    lock = threading.Lock()

    def fn():
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.05)
        with lock:
            active[0] -= 1

    rep = run_dag([Node("x", fn), Node("y", fn)], workers=1)
    assert peak[0] == 1
    assert rep["workers"] == 1


# ---------------------------------------------------------------------------
# end-to-end: bitwise parity + mid-DAG resume on a real model set
# ---------------------------------------------------------------------------

def _hash_outputs(root, algs):
    """sha256 over every byte the pipeline published: primary models/
    + evals/, and each fan-out sibling's models/."""
    h = hashlib.sha256()
    roots = [("", root)] + [(f"train.{a}:", variant_dir(root, f"train.{a}"))
                            for a in algs[1:]]
    for prefix, base in roots:
        for sub in ("models", "evals"):
            top = os.path.join(base, sub)
            for dirpath, dirs, files in os.walk(top):
                dirs.sort()
                for f in sorted(files):
                    p = os.path.join(dirpath, f)
                    h.update(f"{prefix}{sub}/{os.path.relpath(p, top)}"
                             .encode())
                    with open(p, "rb") as fh:
                        h.update(fh.read())
    return h.hexdigest()


def _reset_outputs(root):
    for f in ("ColumnConfig.json", "featureimportance.csv"):
        p = os.path.join(root, f)
        if os.path.exists(p):
            os.remove(p)
    for d in ("models", "modelsBackup", "evals", "tmp"):
        shutil.rmtree(os.path.join(root, d), ignore_errors=True)


def test_pipeline_dag_bitwise_parity_and_resume(tmp_path, rng,
                                                monkeypatch):
    """NN+GBT fan-out + eval through the scheduler produces bitwise
    identical outputs to the same nodes run sequentially; a rerun with
    SHIFU_TPU_RESUME=1 parks completed nodes as ``cached`` and runs
    only the node whose manifest was invalidated."""
    from tests.synth import make_model_set

    root = make_model_set(tmp_path, rng, n_rows=600,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [6],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM",
                                        "TreeNum": 8, "MaxDepth": 3})
    mc_path = os.path.join(root, "ModelConfig.json")
    with open(mc_path) as f:
        mc = json.load(f)
    mc["train"]["numTrainEpochs"] = 4
    with open(mc_path, "w") as f:
        json.dump(mc, f, indent=2)
    algs = ["NN", "GBT"]

    # leg 1: the same node bodies, walked sequentially in list order
    # (pipeline_nodes returns a topological order)
    for n in pipeline_nodes(root, eval_sets=["Eval1"], algorithms=algs,
                            resume=False):
        n.fn()
    seq = _hash_outputs(root, algs)
    assert os.path.exists(os.path.join(root, "evals", "Eval1",
                                       "EvalPerformance.json"))

    # leg 2: scheduled, 2 device workers
    _reset_outputs(root)
    rep = run_dag(pipeline_nodes(root, eval_sets=["Eval1"],
                                 algorithms=algs, resume=False),
                  workers=2, root=root, label="pipeline")
    assert _states(rep) == {"init": DONE, "stats": DONE, "norm": DONE,
                            "train.NN": DONE, "train.GBT": DONE,
                            "eval.Eval1": DONE}
    assert _hash_outputs(root, algs) == seq

    # leg 3: RESUME — invalidate only the eval manifest; everything
    # upstream must park as cached, only eval.Eval1 re-runs
    monkeypatch.setenv("SHIFU_TPU_RESUME", "1")
    os.remove(os.path.join(root, "tmp", "manifests", "eval.Eval1.json"))
    rep = run_dag(pipeline_nodes(root, eval_sets=["Eval1"],
                                 algorithms=algs, resume=True),
                  workers=2, root=root, label="pipeline")
    assert _states(rep) == {"init": CACHED, "stats": CACHED,
                            "norm": CACHED, "train.NN": CACHED,
                            "train.GBT": CACHED, "eval.Eval1": DONE}
    assert _hash_outputs(root, algs) == seq
