"""Pipeline DAG scheduler (tier-1): scheduling semantics on synthetic
graphs, and the end-to-end contract on a real model set — outputs of a
DAG run are bitwise identical to the same nodes walked sequentially,
RESUME parks completed nodes as ``cached``, and a failure poisons only
the failing node's descendants while every independent branch runs.
"""

import hashlib
import json
import os
import shutil
import threading
import time

import pytest

from shifu_tpu import profiling, resilience
from shifu_tpu.pipeline.nodes import pipeline_nodes, variant_dir
from shifu_tpu.pipeline.scheduler import (CACHED, DONE, FAILED, POISONED,
                                          DagError, Node, run_dag)


def _states(report):
    return {r["node"]: r["state"] for r in report["nodes"]}


# ---------------------------------------------------------------------------
# scheduler semantics (synthetic graphs, no subprocesses)
# ---------------------------------------------------------------------------

def test_dag_failure_poisons_descendants_only(tmp_path):
    """b fails → its descendant c is poisoned; the independent d/e
    branch still runs; DagError carries the full report and the abort
    marker names the failing node (dist.py discipline)."""
    ran = []

    def ok(name):
        return lambda: ran.append(name)

    def boom():
        raise OSError("synthetic")

    nodes = [
        Node("a", ok("a")),
        Node("b", boom, deps=("a",)),
        Node("c", ok("c"), deps=("b",)),
        Node("d", ok("d"), deps=("a",)),
        Node("e", ok("e")),
    ]
    with pytest.raises(DagError) as ei:
        run_dag(nodes, workers=2, root=str(tmp_path), label="t")
    rep = ei.value.report
    assert _states(rep) == {"a": DONE, "b": FAILED, "c": POISONED,
                            "d": DONE, "e": DONE}
    assert sorted(ran) == ["a", "d", "e"]
    assert rep["failed"] == "b"
    assert "'c'" in str(ei.value) and "all other" in str(ei.value)
    marker = resilience.check_abort()
    assert marker is not None and marker["site"] == "dag.b"
    resilience.clear_abort()
    resilience.set_abort_scope(None)


def test_dag_report_schema_and_cached(tmp_path):
    """Per-node records carry exactly profiling.DAG_FIELDS; a true
    done_check parks the node as cached without calling fn; the summary
    block carries exactly DAG_SUMMARY_FIELDS."""
    calls = []
    nodes = [
        Node("a", lambda: calls.append("a")),
        Node("b", lambda: calls.append("b"), deps=("a",),
             done_check=lambda: True),
        Node("c", lambda: calls.append("c"), deps=("b",)),
    ]
    rep = run_dag(nodes, workers=2)
    assert _states(rep) == {"a": DONE, "b": CACHED, "c": DONE}
    assert calls == ["a", "c"]
    assert tuple(rep) == profiling.DAG_SUMMARY_FIELDS
    for rec in rep["nodes"]:
        assert tuple(rec) == profiling.DAG_FIELDS
    # critical path covers the chain through real (non-cached) work
    chain = [r["node"] for r in rep["nodes"] if r["critical_path"]]
    assert "a" in chain or "c" in chain
    assert rep["failed"] is None


def test_dag_validation_rejects_bad_graphs():
    with pytest.raises(ValueError, match="duplicate"):
        run_dag([Node("a", lambda: None), Node("a", lambda: None)])
    with pytest.raises(ValueError, match="unknown node"):
        run_dag([Node("a", lambda: None, deps=("ghost",))])
    with pytest.raises(ValueError, match="cycle"):
        run_dag([Node("a", lambda: None, deps=("b",)),
                 Node("b", lambda: None, deps=("a",))])


def test_dag_host_nodes_bypass_device_worker_cap():
    """With a single device slot occupied by a running trainer, a
    host-only node must still be admitted (it unblocks the trainer
    here; if host nodes queued behind the device cap this would time
    out)."""
    release = threading.Event()

    def device_fn():
        assert release.wait(timeout=30), \
            "host-only node queued behind device worker cap"

    nodes = [
        Node("trainer", device_fn, device=True),
        Node("host", release.set, device=False),
    ]
    rep = run_dag(nodes, workers=1)
    assert _states(rep) == {"trainer": DONE, "host": DONE}


def test_dag_device_cap_is_respected():
    """SHIFU_TPU_DAG_WORKERS=1 → two device nodes never overlap."""
    active, peak = [0], [0]
    lock = threading.Lock()

    def fn():
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.05)
        with lock:
            active[0] -= 1

    rep = run_dag([Node("x", fn), Node("y", fn)], workers=1)
    assert peak[0] == 1
    assert rep["workers"] == 1


# ---------------------------------------------------------------------------
# end-to-end: bitwise parity + mid-DAG resume on a real model set
# ---------------------------------------------------------------------------

def _hash_outputs(root, algs):
    """sha256 over every byte the pipeline published: primary models/
    + evals/, and each fan-out sibling's models/."""
    h = hashlib.sha256()
    roots = [("", root)] + [(f"train.{a}:", variant_dir(root, f"train.{a}"))
                            for a in algs[1:]]
    for prefix, base in roots:
        for sub in ("models", "evals"):
            top = os.path.join(base, sub)
            for dirpath, dirs, files in os.walk(top):
                dirs.sort()
                for f in sorted(files):
                    p = os.path.join(dirpath, f)
                    h.update(f"{prefix}{sub}/{os.path.relpath(p, top)}"
                             .encode())
                    with open(p, "rb") as fh:
                        h.update(fh.read())
    return h.hexdigest()


def _reset_outputs(root):
    for f in ("ColumnConfig.json", "featureimportance.csv"):
        p = os.path.join(root, f)
        if os.path.exists(p):
            os.remove(p)
    for d in ("models", "modelsBackup", "evals", "tmp"):
        shutil.rmtree(os.path.join(root, d), ignore_errors=True)


def test_pipeline_dag_bitwise_parity_and_resume(tmp_path, rng,
                                                monkeypatch):
    """NN+GBT fan-out + eval through the scheduler produces bitwise
    identical outputs to the same nodes run sequentially; a rerun with
    SHIFU_TPU_RESUME=1 parks completed nodes as ``cached`` and runs
    only the node whose manifest was invalidated."""
    from tests.synth import make_model_set

    root = make_model_set(tmp_path, rng, n_rows=600,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [6],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM",
                                        "TreeNum": 8, "MaxDepth": 3})
    mc_path = os.path.join(root, "ModelConfig.json")
    with open(mc_path) as f:
        mc = json.load(f)
    mc["train"]["numTrainEpochs"] = 4
    with open(mc_path, "w") as f:
        json.dump(mc, f, indent=2)
    algs = ["NN", "GBT"]

    # leg 1: the same node bodies, walked sequentially in list order
    # (pipeline_nodes returns a topological order). The conftest rig
    # exposes 8 fake devices, so leg 2 runs auto-SLICED and hands each
    # of the two trainers a 4-device lease; pin the same per-node mesh
    # SIZE here — parity depends on mesh size, never on which device
    # indices back it (a k-device mesh compiles one XLA program).
    for n in pipeline_nodes(root, eval_sets=["Eval1"], algorithms=algs,
                            resume=False):
        if n.device:
            monkeypatch.setenv("SHIFU_TPU_MESH_DEVICES",
                               str(n.devices or 8))
        else:
            monkeypatch.delenv("SHIFU_TPU_MESH_DEVICES", raising=False)
        n.fn()
    monkeypatch.delenv("SHIFU_TPU_MESH_DEVICES", raising=False)
    seq = _hash_outputs(root, algs)
    assert os.path.exists(os.path.join(root, "evals", "Eval1",
                                       "EvalPerformance.json"))

    # leg 2: scheduled, 2 device workers
    _reset_outputs(root)
    rep = run_dag(pipeline_nodes(root, eval_sets=["Eval1"],
                                 algorithms=algs, resume=False),
                  workers=2, root=root, label="pipeline")
    assert _states(rep) == {"init": DONE, "stats": DONE, "norm": DONE,
                            "train.NN": DONE, "train.GBT": DONE,
                            "eval.Eval1": DONE}
    assert _hash_outputs(root, algs) == seq

    # leg 3: RESUME — invalidate only the eval manifest; everything
    # upstream must park as cached, only eval.Eval1 re-runs
    monkeypatch.setenv("SHIFU_TPU_RESUME", "1")
    os.remove(os.path.join(root, "tmp", "manifests", "eval.Eval1.json"))
    rep = run_dag(pipeline_nodes(root, eval_sets=["Eval1"],
                                 algorithms=algs, resume=True),
                  workers=2, root=root, label="pipeline")
    assert _states(rep) == {"init": CACHED, "stats": CACHED,
                            "norm": CACHED, "train.NN": CACHED,
                            "train.GBT": CACHED, "eval.Eval1": DONE}
    assert _hash_outputs(root, algs) == seq


# ---------------------------------------------------------------------------
# device-slice allocator (synthetic graphs; conftest rig = 8 fake devices)
# ---------------------------------------------------------------------------

@pytest.fixture()
def sliced8(monkeypatch):
    """Force sliced admission over a declared 8-device pool (no probe)."""
    monkeypatch.setenv("SHIFU_TPU_DAG_SLICE", "1")
    monkeypatch.setenv("SHIFU_TPU_DAG_DEVICES", "8")


def test_dag_slice_leases_disjoint_and_env_exported(sliced8):
    """Two demand-4 nodes on an 8-device pool run CONCURRENTLY (the
    rendezvous barrier would break if they serialized) on provably
    disjoint slices, and each receives the full lease env — the slice
    ids plus both platform visibility variables."""
    seen = {}
    barrier = threading.Barrier(2)

    def fn(name):
        def run(lease_env=None):
            seen[name] = lease_env
            barrier.wait(timeout=30)
        return run

    rep = run_dag([Node("a", fn("a"), devices=4),
                   Node("b", fn("b"), devices=4)], workers=4)
    assert rep["total_devices"] == 8
    assert rep["max_concurrent"] == 2
    slices = {}
    for name, env in seen.items():
        ids = env["SHIFU_TPU_DEVICE_SLICE"]
        slices[name] = {int(x) for x in ids.split(",")}
        assert env["TPU_VISIBLE_DEVICES"] == ids
        assert ("--xla_force_host_platform_device_count=8"
                in env["XLA_FLAGS"])
    assert len(slices["a"]) == len(slices["b"]) == 4
    assert slices["a"].isdisjoint(slices["b"])
    assert (slices["a"] | slices["b"]) <= set(range(8))
    for rec in rep["nodes"]:
        assert rec["devices"] == 4


def test_dag_slice_demand_exceeding_pool_raises(sliced8):
    """A demand the pool can never satisfy raises up front — a lease is
    never silently shrunk and the node must not wait forever."""
    with pytest.raises(ValueError, match="demands 9"):
        run_dag([Node("big", lambda: None, devices=9)])


def test_dag_slice_lease_returned_on_failure(sliced8, tmp_path):
    """A failing demand-8 node must return its lease — the independent
    demand-8 sibling can only be admitted afterwards — while the failed
    node's descendant is poisoned without ever holding devices."""
    ran = []

    def boom(lease_env=None):
        raise OSError("synthetic")

    nodes = [
        Node("a", boom, devices=8),
        Node("c", lambda lease_env=None: ran.append("c"), deps=("a",),
             devices=8),
        Node("b", lambda lease_env=None: ran.append("b"), devices=8),
    ]
    with pytest.raises(DagError) as ei:
        run_dag(nodes, workers=2, root=str(tmp_path), label="t")
    rep = ei.value.report
    assert _states(rep) == {"a": FAILED, "b": DONE, "c": POISONED}
    assert ran == ["b"]
    by = {r["node"]: r for r in rep["nodes"]}
    assert by["a"]["devices"] == 8    # granted, then returned on failure
    assert by["c"]["devices"] == 0    # poisoned: never leased
    resilience.clear_abort()
    resilience.set_abort_scope(None)


def test_dag_slice_demand_descending_dispatch(sliced8):
    """Big slices first-fit before small ones fragment the pool: with
    declaration order [small(2), big(8)], the big node must not starve —
    demand-descending tie-break dispatches it first."""
    done_order = []
    lock = threading.Lock()

    def fn(name):
        def run(lease_env=None):
            with lock:
                done_order.append(name)
        return run

    rep = run_dag([Node("small", fn("small"), devices=2),
                   Node("big", fn("big"), devices=8)], workers=4)
    assert done_order[0] == "big"
    assert _states(rep) == {"small": DONE, "big": DONE}


def test_dag_slice_disabled_keeps_timeshared_report(monkeypatch):
    """SHIFU_TPU_DAG_SLICE=0 → legacy timeshared admission: no pool in
    the summary, device nodes carry devices=None (no lease), host nodes
    devices=0."""
    monkeypatch.setenv("SHIFU_TPU_DAG_SLICE", "0")
    rep = run_dag([Node("x", lambda: None),
                   Node("h", lambda: None, device=False)], workers=1)
    assert rep["total_devices"] is None
    by = {r["node"]: r for r in rep["nodes"]}
    assert by["x"]["devices"] is None
    assert by["h"]["devices"] == 0


def test_dag_timeshared_explicit_demand_caps_mesh(monkeypatch):
    """Timeshared mode still honors a declared demand: the node gets
    SHIFU_TPU_MESH_DEVICES so its mesh size matches what a sliced run
    would compute (keeps A/B legs bitwise comparable)."""
    monkeypatch.setenv("SHIFU_TPU_DAG_SLICE", "0")
    seen = {}

    def fn(lease_env=None):
        seen["env"] = lease_env

    run_dag([Node("x", fn, devices=2)], workers=1)
    assert seen["env"] == {"SHIFU_TPU_MESH_DEVICES": "2"}


def test_dag_slice_shrink_resume_matches(tmp_path, rng, monkeypatch):
    """restore_resharded wiring for grid/refresh nodes resuming on a
    smaller lease: train 10 epochs on the full 8-device pool with a
    checkpoint, resume to 30 under a 4-device lease exported through
    the same seam the scheduler uses (SHIFU_TPU_DEVICE_SLICE — on
    NON-zero-based ids, proving placement independence) — trajectory
    matches the uninterrupted run up to cross-mesh-size reduction
    noise."""
    import numpy as np

    from shifu_tpu.config.model_config import ModelTrainConf
    from shifu_tpu.train import checkpoint as ckpt
    from shifu_tpu.train.trainer import train_nn

    x = rng.normal(0, 1, (600, 4)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    w = np.ones(600, np.float32)

    def conf(epochs):
        return ModelTrainConf.from_dict({
            "numTrainEpochs": epochs, "baggingNum": 2,
            "validSetRate": 0.2,
            "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [6],
                       "ActivationFunc": ["tanh"], "LearningRate": 0.1,
                       "Propagation": "ADAM"}})

    straight = train_nn(conf(30), x, y, w, seed=7)
    d = str(tmp_path / "ck")
    train_nn(conf(10), x, y, w, seed=7, checkpoint_dir=d,
             checkpoint_interval=10)
    assert ckpt.latest_step(d) == 10
    monkeypatch.setenv("SHIFU_TPU_DEVICE_SLICE", "4,5,6,7")  # shrink 8→4
    resumed = train_nn(conf(30), x, y, w, seed=7, checkpoint_dir=d,
                       checkpoint_interval=10)
    assert resumed.val_errors.shape[1] == 20
    np.testing.assert_allclose(straight.val_errors[:, 10:],
                               resumed.val_errors, rtol=2e-3, atol=2e-4)
