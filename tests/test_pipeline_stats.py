"""End-to-end init → stats → norm on a synthetic model set (the
LOCAL-mode CLI pipeline test pattern, SURVEY.md §4.4)."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.config.column_config import load_column_configs
from shifu_tpu.config.model_config import ModelConfig, NormType
from shifu_tpu.processor import init as init_proc
from shifu_tpu.processor import norm as norm_proc
from shifu_tpu.processor import stats as stats_proc
from shifu_tpu.processor.base import ProcessorContext


@pytest.fixture()
def inited(model_set):
    ctx = ProcessorContext.load(model_set)
    assert init_proc.run(ctx) == 0
    return model_set


@pytest.fixture()
def statsed(inited):
    ctx = ProcessorContext.load(inited)
    assert stats_proc.run(ctx) == 0
    return inited


def test_init_builds_column_config(inited):
    ccs = load_column_configs(os.path.join(inited, "ColumnConfig.json"))
    by_name = {c.columnName: c for c in ccs}
    assert by_name["diagnosis"].is_target
    assert by_name["wgt"].is_weight
    assert by_name["rowid"].is_meta
    assert by_name["cat_0"].is_categorical
    assert by_name["num_0"].is_numerical
    assert [c.columnNum for c in ccs] == list(range(len(ccs)))


def test_stats_fills_column_config(statsed):
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    by_name = {c.columnName: c for c in ccs}

    num0 = by_name["num_0"]  # informative column
    assert num0.columnStats.ks is not None and num0.columnStats.ks > 10
    assert num0.columnStats.iv > 0.1
    assert num0.columnBinning.length >= 5
    assert num0.columnBinning.binBoundary[0] == float("-inf")
    # counts arrays are length+1 (trailing missing bin)
    assert len(num0.columnBinning.binCountPos) == num0.columnBinning.length + 1
    assert num0.columnStats.totalCount == 1600
    assert num0.columnStats.mean is not None

    noise = by_name["num_1"]  # pure-noise column
    assert noise.columnStats.ks < num0.columnStats.ks

    cat = by_name["cat_0"]
    assert cat.columnBinning.binCategory == ["aa", "bb", "cc", "dd"]
    assert len(cat.columnBinning.binCountPos) == 5
    assert cat.columnStats.ks > 10
    assert cat.columnStats.distinctCount == 4

    # missing accounting: ~2% injected
    assert 0.0 < num0.columnStats.missingPercentage < 0.1


def test_stats_equal_positive_bins(statsed):
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    num0 = next(c for c in ccs if c.columnName == "num_0")
    pos = np.array(num0.columnBinning.binCountPos[:-1], float)
    assert pos.std() / pos.mean() < 0.25  # near-equal positives per bin


@pytest.mark.parametrize("norm_type", ["ZSCALE", "WOE", "WOE_ZSCORE",
                                       "HYBRID", "ONEHOT", "ZSCALE_INDEX"])
def test_norm_families(statsed, norm_type):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.normalize.normType = NormType.parse(norm_type)
    assert norm_proc.run(ctx) == 0
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    dense, tags = data["dense"], data["tags"]
    assert len(tags) == 1600
    assert not np.isnan(dense).any()
    if norm_type == "ZSCALE":
        assert dense.shape[1] == 8  # 6 numeric + 2 cat (posrate-zscored)
        assert abs(dense.mean()) < 0.5
        assert (np.abs(dense) <= 4.0 + 1e-5).all()
    if norm_type == "WOE":
        assert dense.shape[1] == 8
    if norm_type == "ONEHOT":
        assert dense.shape[1] > 8  # expanded
        assert set(np.unique(dense)).issubset({0.0, 1.0})
    if norm_type == "ZSCALE_INDEX":
        assert dense.shape[1] == 6  # numeric only
        assert data["index"].shape[1] == 2
        assert meta["indexVocabSizes"] == [5, 5]


def test_woe_norm_values_match_lut(statsed):
    """WOE norm output equals the per-bin woe recorded in ColumnConfig."""
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.normalize.normType = NormType.WOE
    norm_proc.run(ctx)
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    ccs = ctx.column_configs
    cat0 = next(c for c in ccs if c.columnName == "cat_0")
    col_idx = meta["denseNames"].index("cat_0")
    got = np.unique(data["dense"][:, col_idx])
    expect = np.asarray(cat0.columnBinning.binCountWoe)
    for g in got:
        assert np.isclose(expect, g, atol=1e-5).any(), g
