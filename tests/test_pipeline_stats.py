"""End-to-end init → stats → norm on a synthetic model set (the
LOCAL-mode CLI pipeline test pattern, SURVEY.md §4.4)."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.config.column_config import load_column_configs
from shifu_tpu.config.model_config import ModelConfig, NormType
from shifu_tpu.processor import init as init_proc
from shifu_tpu.processor import norm as norm_proc
from shifu_tpu.processor import stats as stats_proc
from shifu_tpu.processor.base import ProcessorContext


@pytest.fixture()
def inited(model_set):
    ctx = ProcessorContext.load(model_set)
    assert init_proc.run(ctx) == 0
    return model_set


@pytest.fixture()
def statsed(inited):
    ctx = ProcessorContext.load(inited)
    assert stats_proc.run(ctx) == 0
    return inited


def test_init_builds_column_config(inited):
    ccs = load_column_configs(os.path.join(inited, "ColumnConfig.json"))
    by_name = {c.columnName: c for c in ccs}
    assert by_name["diagnosis"].is_target
    assert by_name["wgt"].is_weight
    assert by_name["rowid"].is_meta
    assert by_name["cat_0"].is_categorical
    assert by_name["num_0"].is_numerical
    assert [c.columnNum for c in ccs] == list(range(len(ccs)))


def test_stats_fills_column_config(statsed):
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    by_name = {c.columnName: c for c in ccs}

    num0 = by_name["num_0"]  # informative column
    assert num0.columnStats.ks is not None and num0.columnStats.ks > 10
    assert num0.columnStats.iv > 0.1
    assert num0.columnBinning.length >= 5
    assert num0.columnBinning.binBoundary[0] == float("-inf")
    # counts arrays are length+1 (trailing missing bin)
    assert len(num0.columnBinning.binCountPos) == num0.columnBinning.length + 1
    assert num0.columnStats.totalCount == 1600
    assert num0.columnStats.mean is not None

    noise = by_name["num_1"]  # pure-noise column
    assert noise.columnStats.ks < num0.columnStats.ks

    cat = by_name["cat_0"]
    assert cat.columnBinning.binCategory == ["aa", "bb", "cc", "dd"]
    assert len(cat.columnBinning.binCountPos) == 5
    assert cat.columnStats.ks > 10
    assert cat.columnStats.distinctCount == 4

    # missing accounting: ~2% injected
    assert 0.0 < num0.columnStats.missingPercentage < 0.1


def test_stats_equal_positive_bins(statsed):
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    num0 = next(c for c in ccs if c.columnName == "num_0")
    pos = np.array(num0.columnBinning.binCountPos[:-1], float)
    assert pos.std() / pos.mean() < 0.25  # near-equal positives per bin


@pytest.mark.parametrize("norm_type", ["ZSCALE", "WOE", "WOE_ZSCORE",
                                       "HYBRID", "ONEHOT", "ZSCALE_INDEX"])
def test_norm_families(statsed, norm_type):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.normalize.normType = NormType.parse(norm_type)
    assert norm_proc.run(ctx) == 0
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    dense, tags = data["dense"], data["tags"]
    assert len(tags) == 1600
    assert not np.isnan(dense).any()
    if norm_type == "ZSCALE":
        assert dense.shape[1] == 8  # 6 numeric + 2 cat (posrate-zscored)
        assert abs(dense.mean()) < 0.5
        assert (np.abs(dense) <= 4.0 + 1e-5).all()
    if norm_type == "WOE":
        assert dense.shape[1] == 8
    if norm_type == "ONEHOT":
        assert dense.shape[1] > 8  # expanded
        assert set(np.unique(dense)).issubset({0.0, 1.0})
    if norm_type == "ZSCALE_INDEX":
        assert dense.shape[1] == 6  # numeric only
        assert data["index"].shape[1] == 2
        assert meta["indexVocabSizes"] == [5, 5]


def test_woe_norm_values_match_lut(statsed):
    """WOE norm output equals the per-bin woe recorded in ColumnConfig."""
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.normalize.normType = NormType.WOE
    norm_proc.run(ctx)
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    ccs = ctx.column_configs
    cat0 = next(c for c in ccs if c.columnName == "cat_0")
    col_idx = meta["denseNames"].index("cat_0")
    got = np.unique(data["dense"][:, col_idx])
    expect = np.asarray(cat0.columnBinning.binCountWoe)
    for g in got:
        assert np.isclose(expect, g, atol=1e-5).any(), g


def test_segment_expansion_pipeline(tmp_path, rng):
    """Segment expansion: K filter expressions create per-segment column
    copies (columnNum = k*N + i, `<name>_seg<k>`) whose stats cover
    only filter-passing rows, and that flow through norm/train/eval
    (BasicUpdater.java:231-249, AddColumnNumAndFilterUDF.java:181-217)."""
    from tests.synth import make_model_set
    from shifu_tpu.processor import eval as eval_proc
    from shifu_tpu.processor import train as train_proc

    root = make_model_set(tmp_path, rng, n_rows=1500,
                          seg_expressions=["num_1 > 0"])
    ctx = ProcessorContext.load(root)
    assert init_proc.run(ctx) == 0
    n_base = len(ctx.column_configs)
    ctx = ProcessorContext.load(root)
    assert stats_proc.run(ctx) == 0

    ccs = load_column_configs(os.path.join(root, "ColumnConfig.json"))
    assert len(ccs) == 2 * n_base
    seg = next(c for c in ccs if c.columnName == "num_0_seg1")
    base = next(c for c in ccs if c.columnName == "num_0")
    assert seg.is_segment and not base.is_segment
    assert seg.columnNum == base.columnNum + n_base
    # segment stats cover only the filtered subpopulation
    assert 0 < seg.columnStats.totalCount < base.columnStats.totalCount
    assert seg.columnStats.ks is not None
    # target/weight copies are demoted to Meta
    tgt_seg = next(c for c in ccs if c.columnName == "diagnosis_seg1")
    assert tgt_seg.is_meta and not tgt_seg.is_target

    for proc in (norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    assert any(n.endswith("_seg1") for n in meta["denseNames"])
    ctx = ProcessorContext.load(root)
    assert eval_proc.run(ctx) == 0
    perf = json.load(open(ctx.path_finder.eval_performance_path("Eval1")))
    assert perf["areaUnderRoc"] > 0.85


def test_rebin_merges_bins_and_keeps_iv(statsed):
    """`stats -rebin` merges bins down while retaining IV
    (ColumnConfigDynamicBinning.run + AutoDynamicBinning.merge)."""
    ctx = ProcessorContext.load(statsed)
    before = {c.columnName: (len(c.columnBinning.binCountPos or []),
                             c.columnStats.iv)
              for c in ctx.column_configs if c.is_candidate}
    assert stats_proc.run_rebin(ctx, expect_bin_num=4) == 0

    ctx2 = ProcessorContext.load(statsed)
    for cc in ctx2.column_configs:
        if not cc.is_candidate or cc.columnName not in before:
            continue
        n_before, iv_before = before[cc.columnName]
        n_after = len(cc.columnBinning.binCountPos or [])
        assert n_after <= max(n_before, 5)
        assert n_after <= 5  # 4 bins + missing
        # count/boundary arrays stay consistent
        bn = cc.columnBinning
        if cc.is_categorical:
            assert len(bn.binCategory) == n_after - 1
        else:
            assert len(bn.binBoundary) == n_after - 1
        assert bn.length == n_after - 1  # real bins, missing slot excluded
        if iv_before is not None and iv_before > 0:
            # merging loses information, so IV cannot rise in exact
            # arithmetic; the ColumnStatsCalculator EPS smoothing of
            # near-empty bins can nudge it up a few percent at most
            assert cc.columnStats.iv is not None
            assert 0.3 * iv_before <= cc.columnStats.iv \
                <= 1.05 * iv_before + 1e-9

    # re-norm still works with "@^"-grouped categories
    ctx3 = ProcessorContext.load(statsed)
    assert norm_proc.run(ctx3) == 0


def test_rebin_grouped_vocab_lut():
    from shifu_tpu.ops.rebin import expand_group_vocab
    lut = expand_group_vocab(["aa@^bb", "cc"])
    assert lut == {"aa": 0, "bb": 0, "cc": 1}


def test_date_stats(tmp_path, rng):
    """Per-date per-column stats (DateStatComputeMapper analog) written
    when dataSet#dateColumnName is set."""
    import pandas as pd
    from tests.synth import make_model_set
    from shifu_tpu.processor import datestat

    root = make_model_set(tmp_path, rng, n_rows=900)
    # inject a date column into data + header + config
    data_file = os.path.join(root, "data", "part-00000")
    hdr_file = os.path.join(root, "data", ".pig_header")
    hdr = open(hdr_file).read().strip().split("|")
    rows = [ln.rstrip("\n").split("|") for ln in open(data_file)]
    dates = ["2026-07-%02d" % (1 + i % 3) for i in range(len(rows))]
    with open(hdr_file, "w") as f:
        f.write("|".join(hdr + ["dt"]) + "\n")
    with open(data_file, "w") as f:
        for r, d in zip(rows, dates):
            f.write("|".join(r + [d]) + "\n")
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["dataSet"]["dateColumnName"] = "dt"
    # dt must be meta so it is not modeled
    with open(os.path.join(root, "columns", "meta.column.names"), "a") as f:
        f.write("dt\n")
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))

    ctx = ProcessorContext.load(root)
    assert init_proc.run(ctx) == 0
    ctx = ProcessorContext.load(root)
    assert stats_proc.run(ctx) == 0  # runs date stats automatically

    out = ctx.path_finder.date_stats_path()
    assert os.path.exists(out)
    ds = pd.read_csv(out)
    assert set(ds["date"]) == {"2026-07-01", "2026-07-02", "2026-07-03"}
    assert set(ds["column"]) == {f"num_{j}" for j in range(6)}
    one = ds[(ds["date"] == "2026-07-01") & (ds["column"] == "num_0")]
    assert float(one["count"].iloc[0]) > 0
    # per-date counts sum to total valid count
    num0 = ds[ds["column"] == "num_0"]
    cc = next(c for c in ctx.column_configs if c.columnName == "num_0")
    assert int(num0["count"].sum() + num0["missing"].sum()) \
        == cc.columnStats.totalCount


@pytest.mark.parametrize("ptype,decimals", [("FLOAT16", 2), ("DOUBLE64", 9),
                                            ("FLOAT7", 6)])
def test_norm_precision_types(statsed, ptype, decimals):
    """-Dshifu.precision.type quantizes normalized output
    (udf/norm/PrecisionType.java)."""
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.normalize._extras["precisionType"] = ptype
    assert norm_proc.run(ctx) == 0
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    assert meta["precisionType"] == ptype
    if ptype == "FLOAT16":
        # every value survives a half-precision round trip unchanged
        d = data["dense"]
        assert np.allclose(d, d.astype(np.float16).astype(np.float32))
    elif ptype == "FLOAT7":
        # FLOAT7's DecimalFormat "#.######" keeps 6 fraction digits
        d = data["dense"]
        assert np.allclose(d, np.round(d.astype(np.float64), 6), atol=1e-7)
    else:
        assert data["dense"].dtype == np.float64


def test_segment_stats_dag_siblings_bitwise(tmp_path, rng):
    """The segment DAG split (`stats -base-only` → one `stats -seg K`
    sibling per expression → `stats -seg-merge`) commits a
    ColumnConfig.json byte-identical to the inline single-node
    expansion, and pipeline_nodes wires norm to the merge sink."""
    import shutil
    from tests.synth import make_model_set
    from shifu_tpu.pipeline.nodes import pipeline_nodes

    root = make_model_set(tmp_path / "inline", rng, n_rows=1200,
                          seg_expressions=["num_1 > 0", "num_0 > 0"])
    ctx = ProcessorContext.load(root)
    assert init_proc.run(ctx) == 0
    twin = os.path.join(str(tmp_path), "dag", "ModelSet")
    os.makedirs(os.path.dirname(twin), exist_ok=True)
    shutil.copytree(root, twin)  # dataPath is absolute → same raw rows

    ctx = ProcessorContext.load(root)
    assert stats_proc.run(ctx) == 0

    assert stats_proc.run(ProcessorContext.load(twin),
                          base_only=True) == 0
    for k in (1, 2):
        assert stats_proc.run_segment(ProcessorContext.load(twin), k) == 0
    assert stats_proc.run_segment_merge(ProcessorContext.load(twin)) == 0

    inline = open(os.path.join(root, "ColumnConfig.json"), "rb").read()
    dag = open(os.path.join(twin, "ColumnConfig.json"), "rb").read()
    assert dag == inline

    nodes = {n.name: n for n in pipeline_nodes(twin, resume=False)}
    assert {"stats.seg.1", "stats.seg.2", "stats.segmerge"} <= set(nodes)
    assert nodes["stats.seg.1"].deps == ("stats",)
    assert nodes["stats.segmerge"].deps == ("stats.seg.1", "stats.seg.2")
    assert nodes["norm"].deps == ("stats.segmerge",)
