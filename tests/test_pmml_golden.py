"""PMML golden files: exports for fixed fixtures are checked into
tests/golden/ and compared structurally, guarding writer drift
(the reference keeps golden specs in src/test/resources and validates
via the external jpmml evaluator — `core/pmml/PMMLTranslatorTest.java`,
`PMMLVerifySuit.java`). A third-party cross-score with pypmml runs
when that package is installed (skip-if-absent: it needs a JVM, not in
this image); golden sidecars additionally pin expected scores so a
semantics change in BOTH writer and evaluator still trips the test.

Regenerate (after an intentional format change):
    python tests/test_pmml_golden.py regen

The goldens pin the full seeded *training trajectory*, not just the
writer: any intentional optimizer/trainer change legitimately shifts
trained weights and requires a regen (last: 2026-08, post-seed trainer
changes drifted lr/nn weights; gbt structure was unaffected). A regen
is only trustworthy because three gates validate it independently of
the pinned trajectory: structural compare at 2e-3 relative tolerance,
the score sidecar (rtol=2e-3 / atol=2e-4), and the independent
evaluator in pmml_external_eval.py agreeing with the sidecar at
rtol=1e-6 / atol=1e-4 — a writer bug that survives all three would
have to corrupt weights, scores, and an unrelated evaluator the same
way.
"""

import json
import os
import sys
import xml.etree.ElementTree as ET

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")

FIXTURES = {
    "nn": dict(algorithm="NN", norm_type="ZSCALE",
               train_params={"NumHiddenLayers": 1, "NumHiddenNodes": [6],
                             "ActivationFunc": ["tanh"],
                             "LearningRate": 0.1, "Propagation": "ADAM"}),
    "lr": dict(algorithm="LR", norm_type="ZSCALE",
               train_params={"LearningRate": 0.1, "Propagation": "ADAM"}),
    "gbt": dict(algorithm="GBT", norm_type="ZSCALE",
                train_params={"TreeNum": 3, "MaxDepth": 3,
                              "LearningRate": 0.1, "Loss": "log"}),
}


def _build_fixture(tmp_dir, kind):
    """Deterministic model set + trained model + PMML export. The rng
    is seeded per-kind, independent of the test session."""
    from tests.synth import make_model_set
    from shifu_tpu.cli import main as cli_main
    from shifu_tpu.processor.base import ProcessorContext

    spec = FIXTURES[kind]
    rng = np.random.default_rng(7700 + len(kind))
    root = make_model_set(tmp_dir, rng, n_rows=800,
                          norm_type=spec["norm_type"],
                          algorithm=spec["algorithm"],
                          train_params=spec["train_params"])
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["numTrainEpochs"] = 12
    json.dump(mc, open(mcp, "w"))
    for cmd in (["init"], ["stats"], ["norm"], ["train"],
                ["export", "-t", "pmml"]):
        assert cli_main(["--dir", root] + cmd) == 0
    ctx = ProcessorContext.load(root)
    pmml_path = ctx.path_finder.pmml_path(0)
    # expected scores over a fixed probe frame, via the built-in
    # evaluator (sidecar-pinned at generation time)
    from shifu_tpu import pmml as pmml_mod
    import pandas as pd
    from shifu_tpu.data.reader import read_raw_table
    df = read_raw_table(ctx.model_config).head(25)
    scores = pmml_mod.evaluate_pmml(open(pmml_path).read(), df)
    return root, pmml_path, np.asarray(scores, np.float64)


def _canonical(el):
    """Nested-tuple canonical form: tags + attr names exact, numeric
    attr values rounded (float formatting may legally drift)."""
    attrs = {}
    for k, v in sorted(el.attrib.items()):
        try:
            attrs[k] = round(float(v), 4)
        except ValueError:
            attrs[k] = v
    return (el.tag.rsplit("}", 1)[-1], tuple(attrs.items()),
            tuple(_canonical(c) for c in el))


def _assert_same_structure(got: ET.Element, want: ET.Element, path="/"):
    gt = got.tag.rsplit("}", 1)[-1]
    wt = want.tag.rsplit("}", 1)[-1]
    assert gt == wt, f"{path}: tag {gt} != {wt}"
    assert sorted(got.attrib) == sorted(want.attrib), \
        f"{path}{gt}: attr names {sorted(got.attrib)} != " \
        f"{sorted(want.attrib)}"
    for k in got.attrib:
        g, w = got.attrib[k], want.attrib[k]
        try:
            gf, wf = float(g), float(w)
            assert abs(gf - wf) <= 2e-3 * max(1.0, abs(wf)), \
                f"{path}{gt}@{k}: {gf} != {wf}"
        except ValueError:
            assert g == w, f"{path}{gt}@{k}: {g!r} != {w!r}"
    assert len(got) == len(want), \
        f"{path}{gt}: {len(got)} children != {len(want)}"
    for i, (gc, wc) in enumerate(zip(got, want)):
        _assert_same_structure(gc, wc, path=f"{path}{gt}[{i}]/")


@pytest.fixture(scope="session")
def built_fixtures(tmp_path_factory):
    """One _build_fixture run per kind per session — several tests
    compare against the same deterministic export instead of each
    re-training identical models."""
    cache = {}

    def get(kind):
        if kind not in cache:
            cache[kind] = _build_fixture(
                str(tmp_path_factory.mktemp(f"pmml_{kind}")), kind)
        return cache[kind]
    return get


def _assert_internal_external_agree(xml, df):
    """Built-in evaluator vs the independent spec implementation: one
    agreement bar for every conformance test."""
    from shifu_tpu import pmml as pmml_mod
    from tests.pmml_external_eval import PMMLScorer
    internal = np.asarray(pmml_mod.evaluate_pmml(xml, df), np.float64)
    external = np.asarray(
        PMMLScorer(xml).score(df.to_dict(orient="list")), np.float64)
    assert np.isfinite(external).all()
    np.testing.assert_allclose(external, internal, rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("kind", sorted(FIXTURES))
def test_pmml_matches_golden(built_fixtures, kind):
    golden_xml = os.path.join(GOLDEN, f"{kind}.pmml")
    golden_scores = os.path.join(GOLDEN, f"{kind}.scores.json")
    assert os.path.exists(golden_xml), \
        "golden missing — run: python tests/test_pmml_golden.py regen"
    _, pmml_path, scores = built_fixtures(kind)
    got = ET.parse(pmml_path).getroot()
    want = ET.parse(golden_xml).getroot()
    _assert_same_structure(got, want)
    # score pinning: evaluator(golden doc) must still produce the
    # scores recorded at generation time, and the fresh export must
    # score the same — catches coordinated writer+evaluator drift
    side = json.load(open(golden_scores))
    np.testing.assert_allclose(scores, np.asarray(side["scores"]),
                               rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("kind", sorted(FIXTURES))
def test_golden_validates_and_scores_with_pypmml(kind):
    """Third-party conformance (PMMLVerifySuit analog) — runs only
    where pypmml (JVM-backed) is installed."""
    pypmml = pytest.importorskip("pypmml")
    golden_xml = os.path.join(GOLDEN, f"{kind}.pmml")
    side = json.load(open(os.path.join(GOLDEN, f"{kind}.scores.json")))
    model = pypmml.Model.fromFile(golden_xml)
    import pandas as pd
    df = pd.DataFrame(side["records"])
    out = model.predict(df)
    col = [c for c in out.columns if "predicted" in c.lower()
           or "probability" in c.lower()]
    assert col, f"no score column in pypmml output {list(out.columns)}"
    np.testing.assert_allclose(
        np.asarray(out[col[-1]], np.float64),
        np.asarray(side["scores"]), rtol=5e-3, atol=5e-4)


def test_golden_structure_valid():
    """The checked-in goldens pass the structural validator — they are
    real PMML 4.2 documents, not stale artifacts."""
    from shifu_tpu import pmml as pmml_mod
    for kind in sorted(FIXTURES):
        root = ET.parse(os.path.join(GOLDEN, f"{kind}.pmml")).getroot()
        problems = pmml_mod.validate_structure(root)
        assert not problems, f"{kind}: {problems}"


def regen():
    import tempfile
    os.makedirs(GOLDEN, exist_ok=True)
    from shifu_tpu.data.reader import read_raw_table
    from shifu_tpu.processor.base import ProcessorContext
    for kind in sorted(FIXTURES):
        with tempfile.TemporaryDirectory() as td:
            root, pmml_path, scores = _build_fixture(td, kind)
            with open(pmml_path) as f:
                xml = f.read()
            with open(os.path.join(GOLDEN, f"{kind}.pmml"), "w") as f:
                f.write(xml)
            ctx = ProcessorContext.load(root)
            df = read_raw_table(ctx.model_config).head(25)
            with open(os.path.join(GOLDEN, f"{kind}.scores.json"),
                      "w") as f:
                json.dump({"scores": scores.tolist(),
                           "records": df.to_dict(orient="list")}, f,
                          indent=1)
            print(f"golden {kind}: {len(xml)} bytes, "
                  f"{len(scores)} pinned scores")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "regen":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        regen()


@pytest.mark.parametrize("kind", sorted(FIXTURES))
def test_golden_scores_with_independent_evaluator(kind):
    """Conformance against a second, independently-written PMML
    implementation (tests/pmml_external_eval.py, derived from the 4.2
    spec, zero shifu_tpu imports) — the PMMLVerifySuit/jpmml analog
    for an image where pypmml cannot be installed. Scores must agree
    with the golden sidecar to 1e-4 (VERDICT r3 next #7)."""
    from tests.pmml_external_eval import PMMLScorer
    golden_xml = os.path.join(GOLDEN, f"{kind}.pmml")
    side = json.load(open(os.path.join(GOLDEN, f"{kind}.scores.json")))
    got = PMMLScorer(open(golden_xml).read()).score(side["records"])
    np.testing.assert_allclose(np.asarray(got, np.float64),
                               np.asarray(side["scores"]),
                               rtol=1e-6, atol=1e-4)


@pytest.mark.parametrize("kind", sorted(FIXTURES))
def test_fresh_export_scores_with_independent_evaluator(built_fixtures,
                                                        kind):
    """A freshly-trained export must also score identically through the
    built-in evaluator and the independent spec implementation."""
    from shifu_tpu.data.reader import read_raw_table
    from shifu_tpu.processor.base import ProcessorContext
    root, pmml_path, _ = built_fixtures(kind)
    ctx = ProcessorContext.load(root)
    df = read_raw_table(ctx.model_config).head(40)
    _assert_internal_external_agree(open(pmml_path).read(), df)


def test_cancer_judgement_pmml_conformance(tmp_path):
    """The reference's own cancer-judgement model set: train → export →
    the independent evaluator agrees with the built-in one to 1e-4 on
    real records (score-agreement bar of PMMLTranslatorTest)."""
    import shutil
    ref = ("/root/reference/src/test/resources/example/cancer-judgement/"
           "ModelStore/ModelSet1")
    if not os.path.isdir(ref):
        pytest.skip("reference cancer-judgement set not present")
    from shifu_tpu.cli import main as cli_main
    from shifu_tpu.data.reader import read_raw_table
    from shifu_tpu.processor.base import ProcessorContext
    root = os.path.join(tmp_path, "cancer")
    shutil.copytree(ref, root)
    # the reference set ships its own trained Encog binaries — clear
    # them so this run's models are the only ones in models/
    shutil.rmtree(os.path.join(root, "models"), ignore_errors=True)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["numTrainEpochs"] = 15
    mc["train"]["baggingNum"] = 1
    # the reference stores dataPath relative to ITS repo root — repoint
    ref_base = os.path.dirname(os.path.dirname(os.path.dirname(ref)))
    data = os.path.join(ref_base, "cancer-judgement", "DataStore",
                        "DataSet1")
    mc["dataSet"]["dataPath"] = data
    mc["dataSet"]["headerPath"] = os.path.join(data, ".pig_header")
    for ev in mc.get("evals") or []:
        ev["dataSet"]["dataPath"] = data
        ev["dataSet"]["headerPath"] = os.path.join(data, ".pig_header")
    json.dump(mc, open(mcp, "w"))
    for cmd in (["init"], ["stats"], ["norm"], ["train"],
                ["export", "-t", "pmml"]):
        assert cli_main(["--dir", root] + cmd) == 0, cmd
    ctx = ProcessorContext.load(root)
    df = read_raw_table(ctx.model_config).head(60)
    _assert_internal_external_agree(
        open(ctx.path_finder.pmml_path(0)).read(), df)
