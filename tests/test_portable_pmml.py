"""Portable numpy-only scorers + PMML export/conformance.

Mirrors the reference's Independent*Model tests
(`core/dtrain/{NNModelEvalAndScore,IndependentTreeModel}Test.java`) and
jpmml conformance tests (`core/pmml/PMMLTranslatorTest.java`,
`PMMLVerifySuit.java`): the portable scorer must agree with the native
JAX scorer bit-for-bit-ish, and a PMML document scored from RAW records
must agree with the pipeline's normalized-scoring path.
"""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from shifu_tpu.cli import main as cli_main
from shifu_tpu.processor.base import ProcessorContext


def _pipeline(model_set, *extra):
    for cmd in (["init"], ["stats"], ["norm"], ["train"], *extra):
        assert cli_main(["--dir", model_set] + list(cmd)) == 0
    return model_set


@pytest.fixture()
def trained_nn(model_set):
    return _pipeline(model_set)


def _norm_blocks(root):
    from shifu_tpu.processor import norm as norm_proc
    ctx = ProcessorContext.load(root)
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    return ctx, data, meta


# ---------------------------------------------------------------------------
# Portable scorer parity
# ---------------------------------------------------------------------------

def test_portable_imports_without_jax(trained_nn):
    """The zero-dependency property itself: importing and using
    shifu_tpu.portable must not pull jax into the process."""
    models_dir = os.path.join(trained_nn, "models")
    code = (
        "import sys\n"
        "import numpy as np\n"
        "from shifu_tpu.portable import PortableScorer\n"
        "assert 'jax' not in sys.modules, 'portable pulled in jax'\n"
        f"s = PortableScorer({models_dir!r})\n"
        "out = s.score(dense=np.zeros((3, s.models[0][2][0]['w'].shape[0]),"
        " np.float32))\n"
        "assert out['mean'].shape == (3,)\n"
        "assert 'jax' not in sys.modules, 'scoring pulled in jax'\n"
    )
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    subprocess.run([sys.executable, "-c", code], check=True, env=env)


def test_portable_nn_matches_native(trained_nn):
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.portable import PortableScorer
    ctx, data, meta = _norm_blocks(trained_nn)
    native = Scorer.from_dir(ctx.path_finder.models_path())
    portable = PortableScorer(ctx.path_finder.models_path())
    a = native.score(data["dense"])["mean"]
    b = portable.score(dense=data["dense"])["mean"]
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_portable_softmax_matches_native():
    """NATIVE multi-class specs (softmax head) score identically through
    the numpy-only forward."""
    import jax
    from shifu_tpu.models import nn as nn_mod
    from shifu_tpu.portable import mlp_forward
    spec = nn_mod.MLPSpec(input_dim=5, hidden_dims=(8,),
                          activations=("tanh",), output_dim=3,
                          output_activation="softmax", loss="log")
    params = nn_mod.init_params(spec, jax.random.PRNGKey(3))
    x = np.random.default_rng(0).normal(0, 1, (16, 5)).astype(np.float32)
    native = np.asarray(nn_mod.forward(spec, params, x))
    np_params = jax.tree.map(np.asarray, params)
    portable = mlp_forward(
        {"activations": ["tanh"], "output_activation": "softmax",
         "output_dim": 3}, np_params, x)
    np.testing.assert_allclose(native, portable, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(portable.sum(axis=1), 1.0, rtol=1e-5)


@pytest.mark.parametrize("algorithm", ["GBT", "RF"])
def test_portable_trees_match_native(tmp_path, rng, algorithm):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1500, algorithm=algorithm,
                          train_params={"TreeNum": 5, "MaxDepth": 4,
                                        "LearningRate": 0.1,
                                        "Loss": "squared"})
    _pipeline(root)
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.portable import PortableScorer
    from shifu_tpu.processor import norm as norm_proc
    from shifu_tpu.processor.norm import load_dataset_for_columns
    ctx = ProcessorContext.load(root)
    cols = norm_proc.selected_candidates(ctx.column_configs)
    dset = load_dataset_for_columns(ctx.model_config, ctx.column_configs,
                                    cols)
    vlen = np.asarray([len(v) for v in dset.vocabs], np.int32)
    raw_codes = np.where(dset.cat_codes < 0, vlen[None, :],
                         dset.cat_codes).astype(np.int32) \
        if dset.cat_codes.shape[1] else dset.cat_codes
    native = Scorer.from_dir(ctx.path_finder.models_path())
    portable = PortableScorer(ctx.path_finder.models_path())
    a = native.score(dset.numeric, raw_dense=dset.numeric,
                     raw_codes=raw_codes)["mean"]
    b = portable.score(raw_dense=dset.numeric, raw_codes=raw_codes)["mean"]
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_portable_wdl_matches_native(tmp_path, rng):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1200, algorithm="WDL",
                          norm_type="ZSCALE_INDEX",
                          train_params={"NumHiddenNodes": [8],
                                        "ActivationFunc": ["relu"],
                                        "EmbedSize": 4,
                                        "LearningRate": 0.05})
    _pipeline(root)
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.portable import PortableScorer
    ctx, data, meta = _norm_blocks(root)
    native = Scorer.from_dir(ctx.path_finder.models_path())
    portable = PortableScorer(ctx.path_finder.models_path())
    a = native.score(data["dense"], data["index"])["mean"]
    b = portable.score(dense=data["dense"], index=data["index"])["mean"]
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# PMML export + conformance
# ---------------------------------------------------------------------------

def _raw_eval_frame(root):
    """The raw eval split as a string DataFrame (missing token '?' →
    empty)."""
    hdr = open(os.path.join(root, "evaldata", ".pig_header")).read() \
        .strip().split("|")
    rows = [ln.split("|") for ln in
            open(os.path.join(root, "evaldata", "part-00000"))
            .read().splitlines()]
    df = pd.DataFrame(rows, columns=hdr, dtype=str)
    return df.replace("?", "")


def _native_scores(root, df):
    from shifu_tpu.eval.model_runner import ModelRunner
    runner = ModelRunner.from_model_set(root)
    return runner.score_frame(df)["mean"]


def test_pmml_nn_zscore_conformance(trained_nn):
    from shifu_tpu import pmml as pmml_mod
    assert cli_main(["--dir", trained_nn, "export", "-t", "pmml"]) == 0
    path = ProcessorContext.load(trained_nn).path_finder.pmml_path(0)
    assert os.path.exists(path)
    df = _raw_eval_frame(trained_nn).head(200)
    got = pmml_mod.evaluate_pmml(open(path).read(), df)
    want = _native_scores(trained_nn, df.copy())
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pmml_nn_woe_conformance(tmp_path, rng):
    from tests.synth import make_model_set
    from shifu_tpu import pmml as pmml_mod
    root = make_model_set(tmp_path, rng, n_rows=1500, norm_type="WOE")
    _pipeline(root)
    assert cli_main(["--dir", root, "export", "-t", "pmml"]) == 0
    path = ProcessorContext.load(root).path_finder.pmml_path(0)
    df = _raw_eval_frame(root).head(200)
    got = pmml_mod.evaluate_pmml(open(path).read(), df)
    want = _native_scores(root, df.copy())
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pmml_gbt_conformance(tmp_path, rng):
    from tests.synth import make_model_set
    from shifu_tpu import pmml as pmml_mod
    root = make_model_set(tmp_path, rng, n_rows=1500, algorithm="GBT",
                          train_params={"TreeNum": 4, "MaxDepth": 3,
                                        "LearningRate": 0.1,
                                        "Loss": "log"})
    _pipeline(root)
    assert cli_main(["--dir", root, "export", "-t", "pmml"]) == 0
    path = ProcessorContext.load(root).path_finder.pmml_path(0)
    df = _raw_eval_frame(root).head(150)
    # unseen categories must route like the native scorer (missing-bin →
    # default-direction child, expressed as isNotIn in the PMML)
    df.loc[df.index[:10], "cat_0"] = "never_seen_in_training"
    got = pmml_mod.evaluate_pmml(open(path).read(), df)

    from shifu_tpu.eval.model_runner import ModelRunner
    runner = ModelRunner.from_model_set(root)
    want = runner.score_frame(df.copy())["mean"]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pmml_unsupported_norm_rejected(tmp_path, rng):
    from tests.synth import make_model_set
    from shifu_tpu import pmml as pmml_mod
    from shifu_tpu.models.spec import list_models, load_model
    root = make_model_set(tmp_path, rng, n_rows=800, norm_type="ONEHOT")
    _pipeline(root)
    ctx = ProcessorContext.load(root)
    kind, meta, params = load_model(
        list_models(ctx.path_finder.models_path())[0])
    with pytest.raises(ValueError):
        pmml_mod.build_pmml(ctx.model_config, ctx.column_configs, kind,
                            meta, params)


# ---------------------------------------------------------------------------
# Bagging export variants (ExportModelProcessor ONE_BAGGING / UME)
# ---------------------------------------------------------------------------

def _bagged_nn_set(tmp_path, rng):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1200,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [6],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM"})
    import json
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["baggingNum"] = 2
    mc["train"]["baggingSampleRate"] = 0.8
    json.dump(mc, open(mcp, "w"))
    return _pipeline(root)


def test_export_bagging_single_file(tmp_path, rng):
    """`export -t bagging` packs all bags into ONE spec the portable
    scorer ensembles (ONE_BAGGING_MODEL, ExportModelProcessor:140-174)."""
    root = _bagged_nn_set(tmp_path, rng)
    assert cli_main(["--dir", root, "export", "-t", "bagging"]) == 0
    from shifu_tpu.models.spec import load_model
    from shifu_tpu.portable import PortableScorer, score_model
    one = os.path.join(root, "onebagging")
    files = os.listdir(one)
    assert len(files) == 1
    kind, meta, params = load_model(os.path.join(one, files[0]))
    assert kind == "bagging" and len(meta["members"]) == 2

    ctx, data, _ = _norm_blocks(root)
    dense = data["dense"][:100]
    merged = score_model(kind, meta, params, dense=dense)
    per_bag = PortableScorer(
        [ctx.path_finder.model_path(i, "nn") for i in range(2)])
    want = per_bag.score(dense=dense)["mean"]
    np.testing.assert_allclose(merged, want, rtol=1e-5, atol=1e-6)


def test_export_baggingpmml_conformance(tmp_path, rng):
    """`export -t baggingpmml` emits ONE MiningModel averaging the bag
    networks; scoring it from raw records matches the per-bag mean
    (ONE_BAGGING_PMML_MODEL, ExportModelProcessor:192-207)."""
    root = _bagged_nn_set(tmp_path, rng)
    assert cli_main(["--dir", root, "export", "-t", "baggingpmml"]) == 0
    from shifu_tpu import pmml as pmml_mod
    from shifu_tpu.config.model_config import ModelConfig
    mc_name = ModelConfig.load(root).model_set_name
    path = os.path.join(root, "pmmls", f"{mc_name}.pmml")
    assert os.path.exists(path)
    df = _raw_eval_frame(root).head(150)
    got = pmml_mod.evaluate_pmml(open(path).read(), df)
    want = _native_scores(root, df.copy())
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_export_woe_info(trained_nn):
    assert cli_main(["--dir", trained_nn, "export", "-t", "woe"]) == 0
    txt = open(os.path.join(trained_nn, "varwoe_info.txt")).read()
    assert "MISSING\t" in txt
    assert "(-∞," in txt        # numeric interval rows
    assert "num_0" in txt


def test_export_ume_plugin_contract(trained_nn, monkeypatch, tmp_path):
    """Without a configured exporter: rc=3 (reference's
    ClassNotFoundException path). With one: instantiated with the
    ModelConfig and .translate() called."""
    monkeypatch.delenv("SHIFU_TPU_UME_EXPORTER", raising=False)
    assert cli_main(["--dir", trained_nn, "export", "-t", "ume"]) == 3

    plug = tmp_path / "ume_plug.py"
    plug.write_text(
        "calls = []\n"
        "class Exporter:\n"
        "    def __init__(self, mc):\n"
        "        self.mc = mc\n"
        "    def translate(self, name, params):\n"
        "        calls.append((name, params['baggingMode']))\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.setenv("SHIFU_TPU_UME_EXPORTER", "ume_plug:Exporter")
    assert cli_main(["--dir", trained_nn, "export", "-t",
                     "baggingume"]) == 0
    import ume_plug
    assert ume_plug.calls and ume_plug.calls[0][1] is True
