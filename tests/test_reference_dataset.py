"""End-to-end on the reference's OWN tutorial model set
(cancer-judgement, the fixture `ShifuCLITest.java:94-336` drives
through createNewModel → ... → exportModel). The reference ModelConfig
loads UNCHANGED — only the data paths are repointed at the mounted
copy — proving on-disk config compatibility plus full-pipeline quality
on real Shifu data. Skipped when the reference checkout is absent
(end-user machines)."""

import json
import os

import pytest

REF = "/root/reference/src/test/resources/example/cancer-judgement"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not mounted")


@pytest.fixture()
def cancer_set(tmp_path):
    """The reference ModelSet1 config with dataPath repointed (the
    reference stores paths relative to its repo root)."""
    root = tmp_path / "cancer-judgement"
    root.mkdir()
    raw = json.load(open(os.path.join(REF, "ModelStore", "ModelSet1",
                                      "ModelConfig.json")))
    raw["dataSet"]["dataPath"] = os.path.join(REF, "DataStore", "DataSet1")
    raw["dataSet"]["headerPath"] = os.path.join(
        REF, "DataStore", "DataSet1", ".pig_header")
    # eval set: the bundled EvalSet1 split
    for ev in raw.get("evals") or []:
        ev["dataSet"]["dataPath"] = os.path.join(REF, "DataStore",
                                                 "EvalSet1")
        ev["dataSet"]["headerPath"] = os.path.join(
            REF, "DataStore", "EvalSet1", ".pig_header")
    # keep runtime sane for CI: the reference trains 5 bags × 100
    # epochs of a 45×45 sigmoid net; 2 bags × 40 epochs shows the same
    # pipeline with the same architecture
    raw["train"]["baggingNum"] = 2
    raw["train"]["numTrainEpochs"] = 40
    json.dump(raw, open(root / "ModelConfig.json", "w"), indent=1)
    return str(root)


def test_reference_modelconfig_loads_verbatim():
    """The untouched Jackson-written config parses with every section
    intact (round-trip safety is covered by config tests; this pins
    the REAL file)."""
    from shifu_tpu.config.model_config import Algorithm, ModelConfig
    mc = ModelConfig.load(os.path.join(REF, "ModelStore", "ModelSet1"))
    assert mc.basic.name == "cancer-judgement"
    assert mc.train.algorithm is Algorithm.NN
    assert mc.train.baggingNum == 5
    assert mc.dataSet.posTags == ["M"]
    assert mc.train.get_param("NumHiddenNodes") == [45, 45]
    assert [a.lower() for a in mc.train.get_param("ActivationFunc")] == \
        ["sigmoid", "sigmoid"]


def test_cancer_judgement_end_to_end(cancer_set):
    """init → stats → norm → train → eval on the real dataset: the
    north-star acceptance is matched AUC, and this separable dataset
    must score ≥0.95 eval AUC (the reference wiki reports ~0.99 for
    its NN on this data)."""
    from shifu_tpu.processor import (eval as eval_proc, init as init_proc,
                                     norm as norm_proc,
                                     stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    for proc in (init_proc, stats_proc, norm_proc, train_proc, eval_proc):
        ctx = ProcessorContext.load(cancer_set)
        assert proc.run(ctx) == 0

    ccs = json.load(open(os.path.join(cancer_set, "ColumnConfig.json")))
    target = [c for c in ccs if c["columnName"] == "diagnosis"]
    assert target and target[0]["columnType"] is not None
    # weight column flagged, stats filled on a real numeric column
    num = [c for c in ccs if c["columnName"] == "column_4"][0]
    assert num["columnStats"]["ks"] > 0

    perf_path = ProcessorContext.load(cancer_set) \
        .path_finder.eval_performance_path("EvalA")
    if not os.path.exists(perf_path):
        # eval-set name from the reference config
        mc = json.load(open(os.path.join(cancer_set, "ModelConfig.json")))
        name = (mc.get("evals") or [{}])[0].get("name", "Eval1")
        perf_path = ProcessorContext.load(cancer_set) \
            .path_finder.eval_performance_path(name)
    perf = json.load(open(perf_path))
    assert perf["areaUnderRoc"] > 0.95, perf["areaUnderRoc"]
    models = os.listdir(os.path.join(cancer_set, "models"))
    assert sorted(models) == ["model0.nn", "model1.nn"]


@pytest.mark.parametrize("ms,norm", [("ModelSet2", "WOE"),
                                     ("ModelSet3", "WOE_ZSCORE")])
def test_reference_woe_modelsets_end_to_end(tmp_path, ms, norm):
    """The WOE / WOE_ZSCORE variants of the bundled model sets run the
    full pipeline too (NormalizerTest's norm families against real
    configs)."""
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.processor import (eval as eval_proc, init as init_proc,
                                     norm as norm_proc,
                                     stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    raw = json.load(open(os.path.join(REF, "ModelStore", ms,
                                      "ModelConfig.json")))
    assert raw["normalize"]["normType"].upper() == norm
    root = tmp_path / ms
    root.mkdir()
    raw["dataSet"]["dataPath"] = os.path.join(REF, "DataStore", "DataSet1")
    raw["dataSet"]["headerPath"] = os.path.join(
        REF, "DataStore", "DataSet1", ".pig_header")
    for ev in raw.get("evals") or []:
        ev["dataSet"]["dataPath"] = os.path.join(REF, "DataStore",
                                                 "EvalSet1")
        ev["dataSet"]["headerPath"] = os.path.join(
            REF, "DataStore", "EvalSet1", ".pig_header")
    raw["train"]["baggingNum"] = 1
    raw["train"]["numTrainEpochs"] = 30
    json.dump(raw, open(root / "ModelConfig.json", "w"), indent=1)
    # the reference workflow scaffolds these via `shifu new`
    # (CreateModelProcessor); the fixture config references them
    (root / "columns").mkdir()
    for name in ("meta.column.names", "categorical.column.names"):
        (root / "columns" / name).write_text("")

    for proc in (init_proc, stats_proc, norm_proc, train_proc, eval_proc):
        ctx = ProcessorContext.load(str(root))
        assert proc.run(ctx) == 0
    mc = ModelConfig.load(str(root))
    name = mc.evals[0].name
    perf = json.load(open(ProcessorContext.load(str(root))
                          .path_finder.eval_performance_path(name)))
    assert perf["areaUnderRoc"] > 0.95, (ms, perf["areaUnderRoc"])
