"""ROADMAP item 1, closed loop (tier-1): drift breach → warm-start
retrain in a challenger workspace → eval guardrail vs the incumbent →
atomic registry promotion → in-place hot swap into the running fleet →
instant rollback.

Contracts drilled here:

- END-TO-END: a shifted window arrives at the watch loop, the PSI SLO
  breaches, the controller retrains warm, the guardrail passes, the
  challenger publishes atomically and hot-swaps into the live fleet —
  observed by a concurrently-scoring client with ZERO failed requests,
  zero steady-state compile misses, and the SAME service object (no
  restart).
- ADVERSARIAL TWIN: the same drill with a sabotaged challenger is
  REFUSED by the guardrail — HEAD unmoved, incumbent still serving.
- GUARDRAIL MATRIX: better / within-tolerance / worse / eval-faulted
  → promote / promote / hold / hold, each decision a `refresh` event
  in the metrics store.
- CHAOS: an injected fault at EVERY `refresh.*` site leaves the
  incumbent serving and HEAD unmoved or cleanly rolled back, with no
  `.tmp` residue, and a clean rerun promotes (rerun-recovers). SIGKILL
  mid-refresh holds the same invariant across a process boundary.
- HYSTERESIS: breaches during an in-flight refresh or inside the
  cooldown window coalesce into the running one — counted, evented,
  and visible in `shifu health`.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from shifu_tpu import registry, resilience
from shifu_tpu.cli import main as cli_main
from shifu_tpu.data import pipeline
from shifu_tpu.obs.health import store as health_store
from shifu_tpu.obs.health.refresh import RefreshController
from shifu_tpu.processor.base import ProcessorContext
from shifu_tpu.serve.fleet import FleetService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LADDER = (1, 4)   # two tiny buckets keep AOT warms cheap in tier-1


@pytest.fixture(autouse=True)
def _refresh_isolation(monkeypatch):
    for k in ("SHIFU_TPU_METRICS", "SHIFU_TPU_SLO_FILE",
              "SHIFU_TPU_ALERT_WEBHOOK", "SHIFU_TPU_TRACE",
              "SHIFU_TPU_FAULT"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.01")
    resilience.reset_faults()
    yield
    resilience.reset_faults()


@pytest.fixture(scope="module")
def trained_set(tmp_path_factory):
    """ONE trained tiny model set per module (private rng — the
    golden-file tests share the session stream); tests copy it."""
    from tests.synth import make_model_set
    base = tmp_path_factory.mktemp("refresh_base")
    ms = make_model_set(base, np.random.default_rng(11), n_rows=400)
    cfg_path = os.path.join(ms, "ModelConfig.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    cfg["train"]["numTrainEpochs"] = 8
    with open(cfg_path, "w") as f:
        json.dump(cfg, f, indent=2)
    for cmd in ("init", "stats", "norm", "train"):
        assert cli_main(["--dir", ms, cmd]) == 0, cmd
    return ms


def _clone_set(trained_set, tmp_path):
    """Per-test copy. Its configs still point at the ORIGINAL data
    dirs (absolute paths) — fine for reads; tests inject drift through
    in-process windows, never by rewriting the shared data files."""
    ms = os.path.join(str(tmp_path), "ModelSet")
    shutil.copytree(trained_set, ms)
    return ms


def _raw_frame(trained_set):
    import pandas as pd
    hdr = open(os.path.join(trained_set, "data",
                            ".pig_header")).read().strip().split("|")
    return pd.read_csv(os.path.join(trained_set, "data", "part-00000"),
                       sep="|", names=hdr, dtype=str)


def _shift_numerics(df, delta):
    out = df.copy()
    for col in out.columns:
        if not col.startswith("num_"):
            continue
        v = out[col].to_numpy(dtype=object).copy()
        for i, s in enumerate(v):
            try:
                v[i] = f"{float(s) + delta:.6f}"
            except (TypeError, ValueError):
                pass
        out[col] = v
    return out


def _publish_incumbent(ms, tmp_path, name="m"):
    reg = os.path.join(str(tmp_path), "reg")
    v1 = registry.publish(reg, name, os.path.join(ms, "models"),
                          ladder=LADDER)
    return reg, v1


def _no_tmp_residue(root):
    return [os.path.join(d, f) for d, _dirs, fs in os.walk(root)
            for f in fs if f.startswith(".tmp.")]


def _controller(ms, reg, fleet=None, **kw):
    kw.setdefault("tolerance", 0.2)
    kw.setdefault("cooldown_s", 0.0)
    return RefreshController(ProcessorContext.load(ms),
                             registry_root=reg, model_name="m",
                             fleet=fleet, **kw)


# ---------------------------------------------------------------------------
# the acceptance drill: shift → breach → retrain → guardrail → promote
# → in-place swap, observed by a live scoring client
# ---------------------------------------------------------------------------

def test_refresh_drill_end_to_end(trained_set, tmp_path, monkeypatch):
    from shifu_tpu.obs.health import watch as watch_mod

    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    ms = _clone_set(trained_set, tmp_path)
    reg, v1 = _publish_incumbent(ms, tmp_path)
    with open(os.path.join(ms, "slo.json"), "w") as f:
        json.dump({"slos": [
            {"name": "drift", "metric": "drift.psi_max", "op": "<=",
             "warn": 0.02, "breach": 0.05, "window_s": 86400.0,
             "agg": "last"}]}, f)
    df = _raw_frame(trained_set)
    shifted = _shift_numerics(df, delta=0.5)

    with FleetService(reg, workspace_root=ms, hbm_budget_mb=0) as fleet:
        _, _, man = registry.resolve(reg, "m")
        x = np.random.default_rng(3).normal(
            0, 1, (3, man["input_dim"])).astype(np.float32)
        before = np.asarray(fleet.submit("m", dense=x)["mean"])
        svc_before = fleet._entries["m"].service
        ctl = _controller(ms, reg, fleet=fleet)
        # the window accumulates old + newly-arrived shifted traffic
        ctl.note_window(df)

        # live scoring client rides through the whole refresh
        stop, failures, served = threading.Event(), [], [0]

        def client():
            while not stop.is_set():
                try:
                    fleet.submit("m", dense=x, timeout=30.0)
                    served[0] += 1
                except Exception as e:  # noqa: BLE001 — any miss fails
                    failures.append(e)

        pipeline.drain_stage_timers()   # fence off warm-up compiles
        t = threading.Thread(target=client, daemon=True)
        t.start()
        try:
            # one watch tick over the shifted window: drift observe →
            # PSI breach → the controller's full pipeline
            rc = watch_mod.run_monitor(ProcessorContext.load(ms),
                                       interval_s=0.0, iterations=1,
                                       windows=[shifted], refresh=ctl)
        finally:
            stop.set()
            t.join(timeout=30)
        stages = pipeline.drain_stage_timers()

        assert rc == 0
        assert ctl.last_outcome == "promoted", ctl.stats()
        # atomic promotion: HEAD moved, manifest carries the verdict
        assert registry.head(reg, "m") == "v002"
        _, _, man2 = registry.resolve(reg, "m")
        assert man2["refresh"]["refreshed_from"] == v1
        assert man2["refresh"]["challenger_auc"] >= \
            man2["refresh"]["incumbent_auc"] - 0.2
        # in-place swap: same service object (no restart), counted,
        # and NOTHING recompiled anywhere in the breach→swap window
        assert fleet._entries["m"].service is svc_before
        assert fleet.stats()["fleet"]["swaps"] == 1
        assert stages.get("compile_cache_misses", 0) == 0, stages
        assert stages.get("refresh_train_s", 0) > 0
        assert stages.get("fleet_swap_s", 0) > 0
        # the live client never saw a failed request, and the swap
        # really changed what scores come back
        assert not failures, failures[:3]
        assert served[0] > 0
        after = np.asarray(fleet.submit("m", dense=x)["mean"])
        assert not np.array_equal(before, after)

    # the full story landed in the store: drift → breach → refresh
    st = health_store.store(ms)
    names = [e["name"] for e in st.events(limit=50)]
    for want in ("event.drift", "event.breach", "event.refresh"):
        assert want in names, names
    phases = [e["tags"]["phase"] for e in st.events(limit=50,
                                                    names=["refresh"])]
    for want in ("scheduled", "guardrail", "promoted"):
        assert want in phases, phases
    assert not _no_tmp_residue(ms) and not _no_tmp_residue(reg)


def test_sabotaged_challenger_is_held_by_guardrail(trained_set,
                                                   tmp_path,
                                                   monkeypatch):
    """The adversarial twin: identical drill, but the challenger is
    scrambled after training — the guardrail must refuse it."""
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    ms = _clone_set(trained_set, tmp_path)
    reg, v1 = _publish_incumbent(ms, tmp_path)

    def sabotage(clone):
        import jax

        from shifu_tpu.models.spec import (list_models, load_model,
                                           save_model)
        p = list_models(os.path.join(clone, "models"))[0]
        kind, meta, params = load_model(p)
        bad = jax.tree.map(lambda a: np.zeros_like(np.asarray(a)) - 3.0,
                           params)
        save_model(p, kind, meta, bad)

    with FleetService(reg, workspace_root=ms, hbm_budget_mb=0) as fleet:
        _, _, man = registry.resolve(reg, "m")
        x = np.random.default_rng(3).normal(
            0, 1, (3, man["input_dim"])).astype(np.float32)
        before = np.asarray(fleet.submit("m", dense=x)["mean"])
        ctl = _controller(ms, reg, fleet=fleet, post_train=sabotage,
                          tolerance=0.005)
        ctl.note_window(_raw_frame(trained_set))
        out = ctl.handle_breach({"slo": "drift", "state": "breach"})

        assert out == "held"
        assert ctl.stats()["held"] == 1
        # nothing moved: HEAD, the resident version, the scores
        assert registry.head(reg, "m") == v1
        assert fleet.stats()["fleet"]["swaps"] == 0
        after = np.asarray(fleet.submit("m", dense=x)["mean"])
        np.testing.assert_array_equal(before, after)

    st = health_store.store(ms)
    recs = st.events(limit=20, names=["refresh"])
    decisions = [e["tags"].get("decision") for e in recs
                 if e["tags"].get("phase") == "guardrail"]
    assert decisions == ["hold"], recs
    assert not _no_tmp_residue(ms) and not _no_tmp_residue(reg)


# ---------------------------------------------------------------------------
# guardrail decision matrix
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("incumbent,challenger,tolerance,want,why", [
    (0.80, 0.85, 0.005, "promote", "challenger improved"),
    (0.80, 0.80, 0.005, "promote", "challenger improved"),
    (0.80, 0.798, 0.005, "promote", "within tolerance"),
    (0.80, 0.70, 0.005, "hold", "regressed beyond tolerance"),
    (0.80, 0.79, 0.0, "hold", "regressed beyond tolerance"),
])
def test_guardrail_decision_matrix(incumbent, challenger, tolerance,
                                   want, why):
    decision, reason = RefreshController.decide(incumbent, challenger,
                                                tolerance)
    assert (decision, reason) == (want, why)


def test_guardrail_eval_fault_holds_and_events(trained_set, tmp_path,
                                               monkeypatch):
    """A faulted eval can never promote: the run fails closed, HEAD
    stays, and the failure is an event in the store."""
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    ms = _clone_set(trained_set, tmp_path)
    reg, v1 = _publish_incumbent(ms, tmp_path)
    ctl = _controller(ms, reg)
    ctl.note_window(_raw_frame(trained_set))
    monkeypatch.setenv("SHIFU_TPU_FAULT", "refresh.guardrail:oserror:1")
    resilience.reset_faults()
    out = ctl.handle_breach({"slo": "auc", "state": "breach"})
    assert out == "failed"
    assert registry.head(reg, "m") == v1
    st = health_store.store(ms)
    recs = st.events(limit=20, names=["refresh"])
    assert any(e["tags"].get("phase") == "failed" and
               "refresh.guardrail" in e["tags"].get("error", "")
               for e in recs), recs


# ---------------------------------------------------------------------------
# chaos: every refresh.* site — incumbent serving, HEAD sane, rerun
# recovers
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site", ["refresh.schedule", "refresh.guardrail",
                                  "refresh.promote"])
def test_refresh_fault_leaves_head_unmoved_and_rerun_recovers(
        site, trained_set, tmp_path, monkeypatch):
    assert site in resilience.FAULT_SITES
    ms = _clone_set(trained_set, tmp_path)
    reg, v1 = _publish_incumbent(ms, tmp_path)
    ctl = _controller(ms, reg)
    df = _raw_frame(trained_set)
    ctl.note_window(df)
    monkeypatch.setenv("SHIFU_TPU_FAULT", f"{site}:oserror:1")
    resilience.reset_faults()
    out = ctl.handle_breach({"slo": "drift", "state": "breach"})
    assert out == "failed"
    assert registry.head(reg, "m") == v1
    assert not _no_tmp_residue(ms) and not _no_tmp_residue(reg)
    # rerun recovers: clear the fault, next breach promotes cleanly
    monkeypatch.delenv("SHIFU_TPU_FAULT")
    resilience.reset_faults()
    ctl.note_window(df)
    assert ctl.handle_breach({"slo": "drift", "state": "breach"}) \
        == "promoted"
    assert registry.head(reg, "m") == "v002"
    assert not _no_tmp_residue(ms) and not _no_tmp_residue(reg)


def test_swap_fault_rolls_back_instantly(trained_set, tmp_path,
                                         monkeypatch):
    """A failed swap AFTER the publish commit triggers the instant
    rollback: HEAD returns to the incumbent, the fleet never mutated,
    and the next breach promotes cleanly (roll forward)."""
    assert "refresh.swap" in resilience.FAULT_SITES
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    ms = _clone_set(trained_set, tmp_path)
    reg, v1 = _publish_incumbent(ms, tmp_path)
    with FleetService(reg, workspace_root=ms, hbm_budget_mb=0) as fleet:
        _, _, man = registry.resolve(reg, "m")
        x = np.random.default_rng(3).normal(
            0, 1, (3, man["input_dim"])).astype(np.float32)
        before = np.asarray(fleet.submit("m", dense=x)["mean"])
        ctl = _controller(ms, reg, fleet=fleet)
        df = _raw_frame(trained_set)
        ctl.note_window(df)
        monkeypatch.setenv("SHIFU_TPU_FAULT", "refresh.swap:oserror:1")
        resilience.reset_faults()
        out = ctl.handle_breach({"slo": "drift", "state": "breach"})

        assert out == "rolled_back"
        assert ctl.stats()["rolled_back"] == 1
        # HEAD is back on the incumbent; v002 stays as an orphan dir
        # (roll forward is another publish); the incumbent still serves
        assert registry.head(reg, "m") == v1
        after = np.asarray(fleet.submit("m", dense=x)["mean"])
        np.testing.assert_array_equal(before, after)
        st = health_store.store(ms)
        phases = [e["tags"]["phase"]
                  for e in st.events(limit=20, names=["refresh"])]
        assert "rolled_back" in phases

        # rerun recovers across the rollback
        monkeypatch.delenv("SHIFU_TPU_FAULT")
        resilience.reset_faults()
        ctl.note_window(df)
        assert ctl.handle_breach({"slo": "drift", "state": "breach"}) \
            == "promoted"
        assert registry.head(reg, "m") == "v003"
        assert fleet.stats()["fleet"]["swaps"] == 1
    assert not _no_tmp_residue(ms) and not _no_tmp_residue(reg)


_KILL_DRILL = textwrap.dedent("""\
    import os, sys
    import pandas as pd
    ms, reg, data = sys.argv[1], sys.argv[2], sys.argv[3]
    from shifu_tpu.obs.health.refresh import RefreshController
    from shifu_tpu.processor.base import ProcessorContext
    hdr = open(os.path.join(data, ".pig_header")).read().strip().split("|")
    df = pd.read_csv(os.path.join(data, "part-00000"), sep="|",
                     names=hdr, dtype=str)
    ctl = RefreshController(ProcessorContext.load(ms), registry_root=reg,
                            model_name="m", tolerance=0.2, cooldown_s=0.0)
    ctl.note_window(df)
    # the injected SIGKILL fires inside refresh_once — raise if it
    # somehow completes
    ctl.refresh_once({"slo": "drift", "state": "breach"})
    raise SystemExit("refresh survived an injected kill")
""")


def test_sigkill_mid_refresh_incumbent_survives(trained_set, tmp_path):
    """SIGKILL at the promote point, across a real process boundary:
    HEAD unmoved, registry readable, no residue — and the rerun
    promotes."""
    ms = _clone_set(trained_set, tmp_path)
    reg, v1 = _publish_incumbent(ms, tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               SHIFU_TPU_FAULT="refresh.promote:kill:1")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_DRILL, ms, reg,
         os.path.join(trained_set, "data")],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == -9, (proc.returncode, proc.stdout,
                                   proc.stderr)
    assert registry.head(reg, "m") == v1
    registry.resolve(reg, "m")   # raises if HEAD dangles
    assert not _no_tmp_residue(ms) and not _no_tmp_residue(reg)
    # rerun recovers in a clean process (this one)
    ctl = _controller(ms, reg)
    ctl.note_window(_raw_frame(trained_set))
    assert ctl.handle_breach({"slo": "drift", "state": "breach"}) \
        == "promoted"
    assert registry.head(reg, "m") == "v002"


# ---------------------------------------------------------------------------
# hysteresis: cooldown + in-flight coalescing
# ---------------------------------------------------------------------------

def test_breach_storm_coalesces_and_is_visible(trained_set, tmp_path,
                                               monkeypatch, capsys):
    monkeypatch.setenv("SHIFU_TPU_METRICS", "1")
    ms = _clone_set(trained_set, tmp_path)
    ctl = RefreshController(ProcessorContext.load(ms),
                            cooldown_s=3600.0)
    reentrant = []

    def fake_refresh(rec):
        # a second breach lands while this refresh is in flight
        reentrant.append(ctl.handle_breach({"slo": "auc",
                                            "state": "breach"}))
        return "promoted"

    monkeypatch.setattr(ctl, "refresh_once", fake_refresh)
    out = ctl.handle_breach({"slo": "drift", "state": "breach"})
    assert out == "promoted"
    assert reentrant == ["coalesced"]
    # third breach inside the cooldown window also coalesces
    assert ctl.handle_breach({"slo": "drift", "state": "breach"}) \
        == "coalesced"
    assert ctl.stats()["coalesced"] == 2

    st = health_store.store(ms)
    coal = [e for e in st.events(limit=20, names=["refresh"])
            if e["tags"].get("phase") == "coalesced"]
    assert len(coal) == 2 and coal[-1]["tags"]["count"] == 2
    assert st.series("refresh.coalesced")

    # `shifu health` surfaces the coalesced refresh events
    monkeypatch.delenv("SHIFU_TPU_METRICS")
    capsys.readouterr()
    cli_main(["--dir", ms, "health"])
    out_text = capsys.readouterr().out
    assert "refresh" in out_text and "phase=coalesced" in out_text


def test_window_accumulation_is_bounded(trained_set, tmp_path):
    import pandas as pd
    ms = _clone_set(trained_set, tmp_path)
    ctl = RefreshController(ProcessorContext.load(ms), window_rows=100)
    frame = pd.DataFrame({"a": np.arange(60)})
    for _ in range(5):
        ctl.note_window(frame)
    assert ctl.stats()["window_rows_pending"] <= 160   # ≤ cap + 1 frame
    got = ctl._take_window()
    assert len(got) == 100                             # hard cap on take
    assert ctl.stats()["window_rows_pending"] == 0
