"""Remote-source dispatch: a model set whose data lives on a scheme'd
filesystem (fsspec memory://) round-trips init → stats → norm → train →
eval — the `fs/ShifuFileUtils.java` SourceType (HDFS/S3/GS) analog,
exercised without a cluster via fsspec's in-process filesystem."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.data import fs as fs_mod


def test_has_scheme():
    assert fs_mod.has_scheme("s3://bucket/key")
    assert fs_mod.has_scheme("hdfs://nn:8020/data")
    assert fs_mod.has_scheme("memory://x/y")
    assert not fs_mod.has_scheme("/abs/path")
    assert not fs_mod.has_scheme("rel/path")
    assert not fs_mod.has_scheme("")


def test_memory_fs_roundtrip(tmp_path, rng):
    """Full pipeline with dataPath + eval dataPath on memory://."""
    import fsspec
    from tests.synth import make_model_set
    from shifu_tpu.cli import main as cli_main
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=1200)
    mem = fsspec.filesystem("memory")

    # upload raw data + eval data into the in-process remote FS, with
    # the header as the files' first line (no local headerPath)
    mc_path = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mc_path))

    def upload(local_dir, remote_dir, header_path):
        header = open(header_path).read().strip()
        body = open(os.path.join(local_dir, "part-00000")).read()
        with mem.open(f"{remote_dir}/part-00000", "w") as f:
            f.write(header + "\n" + body)

    upload(os.path.join(root, "data"), "/ms/data",
           os.path.join(root, "data", ".pig_header"))
    upload(os.path.join(root, "evaldata"), "/ms/evaldata",
           os.path.join(root, "evaldata", ".pig_header"))

    mc["dataSet"]["dataPath"] = "memory://ms/data"
    mc["dataSet"]["headerPath"] = ""
    mc["dataSet"]["source"] = "HDFS"  # any non-LOCAL SourceType parses
    mc["evals"][0]["dataSet"]["dataPath"] = "memory://ms/evaldata"
    mc["evals"][0]["dataSet"]["headerPath"] = ""
    json.dump(mc, open(mc_path, "w"))

    for cmd in (["init"], ["stats"], ["norm"], ["train"], ["eval"]):
        assert cli_main(["--dir", root] + cmd) == 0, cmd

    ctx = ProcessorContext.load(root)
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85
    # stats really came from the remote data
    cc = json.load(open(os.path.join(root, "ColumnConfig.json")))
    assert any(c.get("columnStats", {}).get("ks") for c in cc)


def test_probe_checks_remote_existence(tmp_path, rng):
    """probe uses the scheme filesystem for existence checks."""
    from tests.synth import make_model_set
    from shifu_tpu.config.inspector import ModelStep, probe
    from shifu_tpu.config.model_config import ModelConfig

    root = make_model_set(tmp_path, rng, n_rows=100)
    mc_path = os.path.join(root, "ModelConfig.json")
    raw = json.load(open(mc_path))
    raw["dataSet"]["dataPath"] = "memory://nope/missing"
    json.dump(raw, open(mc_path, "w"))
    mc = ModelConfig.load(root)
    r = probe(mc, ModelStep.INIT)
    assert not r.status
    assert any("does not exist" in c for c in r.causes)


def test_readahead_hints_defaults(monkeypatch):
    """Remote streaming opens default to a 4 MiB readahead cache; the
    knobs tune or disable each hint independently."""
    monkeypatch.delenv("SHIFU_TPU_FS_CACHE_TYPE", raising=False)
    monkeypatch.delenv("SHIFU_TPU_FS_BLOCK_SIZE", raising=False)
    assert fs_mod.readahead_hints() == {"cache_type": "readahead",
                                        "block_size": 4 * 1024 * 1024}
    monkeypatch.setenv("SHIFU_TPU_FS_CACHE_TYPE", "bytes")
    monkeypatch.setenv("SHIFU_TPU_FS_BLOCK_SIZE", "1048576")
    assert fs_mod.readahead_hints() == {"cache_type": "bytes",
                                        "block_size": 1048576}
    # "none" / 0 drop the hints entirely -> fsspec backend defaults
    monkeypatch.setenv("SHIFU_TPU_FS_CACHE_TYPE", "none")
    monkeypatch.setenv("SHIFU_TPU_FS_BLOCK_SIZE", "0")
    assert fs_mod.readahead_hints() == {}


def test_open_text_carries_hints_to_fsspec(tmp_path, monkeypatch):
    """open_text forwards the hints as fsspec.open kwargs (memory://
    ignores them gracefully, so the default-on hints cannot break
    backends without range-request caching)."""
    import fsspec

    seen = {}
    real_open = fsspec.open

    def spy(path, mode, **kw):
        seen.update(kw)
        return real_open(path, mode, **kw)

    monkeypatch.delenv("SHIFU_TPU_FS_CACHE_TYPE", raising=False)
    monkeypatch.delenv("SHIFU_TPU_FS_BLOCK_SIZE", raising=False)
    monkeypatch.setattr(fsspec, "open", spy)
    mfs = fsspec.filesystem("memory")
    with mfs.open("/hints/part-0", "wb") as f:
        f.write(b"a|b\n1|2\n")
    with fs_mod.open_text("memory://hints/part-0") as f:
        assert f.readline().strip() == "a|b"
    assert seen["cache_type"] == "readahead"
    assert seen["block_size"] == 4 * 1024 * 1024
