"""Elastic-mesh tests (ISSUE 8): topology-portable checkpoint sidecars,
reshard-on-restore across device counts, and the cluster preemption
marker machinery. The conftest rig provides 8 virtual CPU devices, so
"save on 8, restore on 4/1" runs anywhere."""

import json
import os
import threading

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from shifu_tpu import resilience
from shifu_tpu.parallel import dist, mesh as mesh_mod
from shifu_tpu.train import checkpoint as ckpt


def _sharded_state(mesh):
    """A state pytree covering every sidecar class: a 2-D leaf sharded
    on both axes, a 1-D model-sharded leaf, a replicated device leaf,
    and a host-resident numpy leaf."""
    rules = mesh_mod.default_rules()
    return {
        "w0": jax.device_put(
            np.arange(48, dtype=np.float32).reshape(8, 6),
            NamedSharding(mesh, rules.spec("rows", "hidden"))),
        "b0": jax.device_put(np.arange(6, dtype=np.float32),
                             NamedSharding(mesh, rules.spec("hidden"))),
        "rep": jax.device_put(np.float32(3.5), NamedSharding(mesh, P())),
        "host": np.arange(5, dtype=np.int64),
    }


def _like():
    return {"w0": np.zeros((8, 6), np.float32),
            "b0": np.zeros(6, np.float32),
            "rep": np.float32(0.0),
            "host": np.zeros(5, np.int64)}


def test_sidecar_written_and_parsed(tmp_path):
    mesh = mesh_mod.make_mesh(4, 2)
    d = str(tmp_path / "ck")
    ckpt.save_state(d, 3, _sharded_state(mesh))
    side = os.path.join(d, "step_3.sharding.json")
    assert os.path.exists(side)
    with open(side) as f:
        meta = json.load(f)
    assert meta["step"] == 3 and meta["version"] == 1
    assert meta["mesh"]["shape"] == [4, 2]
    assert meta["mesh"]["axes"] == ["data", "model"]
    assert meta["rules"]["hidden"] == "model"
    leaves = meta["leaves"]
    # device leaves recorded with their logical placement; the host
    # leaf has NO entry (that absence is what keeps it host-side on
    # restore)
    assert leaves["['w0']"] == ["data", "model"]
    assert leaves["['b0']"] == ["model"]
    assert leaves["['rep']"] == []
    assert "['host']" not in leaves
    # load_sharding_meta round-trips the same record
    assert ckpt.load_sharding_meta(d, 3)["leaves"] == leaves


@pytest.mark.parametrize("target", ["1dev", "2x1", "4x2", "2x4"])
def test_reshard_roundtrip_bitwise(tmp_path, target):
    """Save on data=4 x model=2; restore onto 1-, 2-, 8-device and a
    transposed 2x4 mesh: values bitwise identical everywhere, host
    leaves stay numpy, and placement follows the re-resolved spec."""
    save_mesh = mesh_mod.make_mesh(4, 2)
    state = _sharded_state(save_mesh)
    d = str(tmp_path / "ck")
    ckpt.save_state(d, 7, state)

    mesh = {"1dev": lambda: mesh_mod.make_mesh(1, 1,
                                               devices=jax.devices()[:1]),
            "2x1": lambda: mesh_mod.make_mesh(2, 1,
                                              devices=jax.devices()[:2]),
            "4x2": lambda: mesh_mod.make_mesh(4, 2),
            "2x4": lambda: mesh_mod.make_mesh(2, 4)}[target]()
    restored = ckpt.restore_resharded(d, _like(), mesh=mesh)
    assert restored is not None
    step, st = restored
    assert step == 7
    for key in ("w0", "b0", "rep"):
        np.testing.assert_array_equal(np.asarray(st[key]),
                                      np.asarray(state[key]))
        assert isinstance(st[key], jax.Array), key
    assert isinstance(st["host"], np.ndarray)
    np.testing.assert_array_equal(st["host"], state["host"])
    # placement re-resolved: on the 4x2 mesh w0 keeps both axes; on the
    # 2x4 mesh the hidden dim (6) does not divide model=4 and must have
    # replicated, loudly — never crashed
    got = st["w0"].sharding.spec
    if target == "4x2":
        assert tuple(got) == ("data", "model"), got
    elif target == "2x4":
        assert len(got) < 2 or got[1] is None, got


def test_missing_sidecar_falls_back_to_replicated(tmp_path):
    mesh = mesh_mod.make_mesh(4, 2)
    state = _sharded_state(mesh)
    d = str(tmp_path / "ck")
    ckpt.save_state(d, 2, state)
    os.remove(os.path.join(d, "step_2.sharding.json"))
    small = mesh_mod.make_mesh(2, 1, devices=jax.devices()[:2])
    # like mirrors the trainer's carry: device leaves device-typed,
    # host leaves numpy — with no sidecar, typing comes from like
    import jax.numpy as jnp
    like = _like()
    like = {k: (v if k == "host" else jnp.asarray(v))
            for k, v in like.items()}
    step, st = ckpt.restore_resharded(d, like, mesh=small)
    assert step == 2
    # like-typed fallback: device leaves land replicated on the current
    # mesh, host leaves stay host — values still bitwise
    for key in ("w0", "b0", "rep"):
        assert isinstance(st[key], jax.Array), key
        np.testing.assert_array_equal(np.asarray(st[key]),
                                      np.asarray(state[key]))
    assert isinstance(st["host"], np.ndarray)


def test_resumed_training_matches_uninterrupted_across_mesh_sizes(
        tmp_path, rng, monkeypatch):
    """The reshard parity gate: train 10 epochs on the 8-device mesh,
    checkpoint, then RESUME on a 1-device mesh to 30 epochs — the loss
    trajectory and final params must match the uninterrupted 30-epoch
    run (up to f32 reduction-order noise across device counts)."""
    from shifu_tpu.config.model_config import ModelTrainConf
    from shifu_tpu.train.trainer import train_nn

    x = rng.normal(0, 1, (600, 4)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    w = np.ones(600, np.float32)

    def conf(epochs):
        return ModelTrainConf.from_dict({
            "numTrainEpochs": epochs, "baggingNum": 2,
            "validSetRate": 0.2,
            "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [6],
                       "ActivationFunc": ["tanh"], "LearningRate": 0.1,
                       "Propagation": "ADAM"}})

    straight = train_nn(conf(30), x, y, w, seed=7)
    d = str(tmp_path / "ck")
    train_nn(conf(10), x, y, w, seed=7, checkpoint_dir=d,
             checkpoint_interval=10)
    assert ckpt.latest_step(d) == 10
    assert ckpt.load_sharding_meta(d, 10) is not None
    monkeypatch.setenv("SHIFU_TPU_MESH_DEVICES", "1")   # shrink 8 → 1
    resumed = train_nn(conf(30), x, y, w, seed=7, checkpoint_dir=d,
                       checkpoint_interval=10)
    # the resumed run reports only its own 20 epochs — they must match
    # epochs 11-30 of the uninterrupted trajectory
    assert resumed.val_errors.shape[1] == 20
    np.testing.assert_allclose(straight.val_errors[:, 10:],
                               resumed.val_errors, rtol=2e-3, atol=2e-4)
    for a, b in zip(straight.params_per_bag[0],
                    resumed.params_per_bag[0]):
        np.testing.assert_allclose(a["w"], b["w"], rtol=5e-3, atol=5e-4)


def test_reshard_fault_injection_names_site(tmp_path, monkeypatch):
    mesh = mesh_mod.make_mesh(4, 2)
    d = str(tmp_path / "ck")
    ckpt.save_state(d, 1, _sharded_state(mesh))
    monkeypatch.setenv("SHIFU_TPU_FAULT", "ckpt.reshard:oserror:1")
    resilience.reset_faults()
    with pytest.raises(OSError, match="injected oserror at ckpt.reshard"):
        ckpt.restore_resharded(d, _like(), mesh=mesh)
    # recoverable: clear the fault, same restore succeeds
    monkeypatch.delenv("SHIFU_TPU_FAULT")
    resilience.reset_faults()
    assert ckpt.restore_resharded(d, _like(), mesh=mesh) is not None


# ---------------------------------------------------------------------------
# preemption consensus machinery (single-process units; the 2-process
# drill lives in test_multihost.py)
# ---------------------------------------------------------------------------

@pytest.fixture()
def abort_scope(tmp_path):
    resilience.set_abort_scope(str(tmp_path / "tmp"))
    resilience.clear_preempt()
    yield str(tmp_path / "tmp")
    resilience.clear_preempt_marker()
    resilience.clear_preempt()
    resilience.set_abort_scope(None)


def test_preempt_marker_roundtrip(abort_scope):
    assert resilience.check_preempt_marker() is None
    resilience.publish_preempt("unit", process=3)
    rec = resilience.check_preempt_marker()
    assert rec["process"] == 3 and rec["note"] == "unit"
    resilience.clear_preempt_marker()
    assert resilience.check_preempt_marker() is None


def test_corrupt_preempt_marker_still_counts(abort_scope):
    os.makedirs(abort_scope, exist_ok=True)
    with open(os.path.join(abort_scope, "preempt.marker"), "w") as f:
        f.write("{not json")
    rec = resilience.check_preempt_marker()
    assert rec is not None and "unreadable" in rec["error"]


def test_watched_collective_observes_peer_preempt(abort_scope):
    """A watched collective that COMPLETES while a peer's preempt
    marker is up must still return its value — and leave the local
    preempt flag set so the caller exits at its own boundary."""
    resilience.publish_preempt("peer", process=1)
    assert dist._watched("unit.ok", lambda: 41 + 1) == 42
    assert resilience.preempt_requested()


def test_watched_collective_grace_raises_preempted(abort_scope,
                                                   monkeypatch):
    """A watched collective still BLOCKED past the grace window after a
    peer preempted must raise Preempted (clean rc-75 path), not wait
    for the much longer barrier timeout."""
    monkeypatch.setenv("SHIFU_TPU_PREEMPT_GRACE_S", "0.4")
    monkeypatch.setenv("SHIFU_TPU_BARRIER_TIMEOUT_S", "60")
    resilience.publish_preempt("peer", process=1)
    release = threading.Event()
    try:
        with pytest.raises(resilience.Preempted):
            dist._watched("unit.block", release.wait)
    finally:
        release.set()


def test_preempt_exit_sync_single_process_noop(abort_scope):
    resilience.preempt_exit_sync(timeout_s=0.1)   # must not block/raise


def test_clear_preempt_marker_sweeps_acks(abort_scope):
    os.makedirs(abort_scope, exist_ok=True)
    for name in ("preempt.marker", "preempt.ack.1", "preempt.ack.2"):
        with open(os.path.join(abort_scope, name), "w") as f:
            f.write("{}")
    resilience.clear_preempt_marker()
    left = [n for n in os.listdir(abort_scope)
            if n.startswith("preempt")]
    assert left == [], left


def test_preempt_marker_fault_absorbed(abort_scope, monkeypatch):
    """An injected fault at dist.preempt_marker must be ABSORBED:
    publish_preempt runs from a signal handler, where raising would
    kill the very checkpoint-and-exit path the marker protects. Peers
    then simply fall back to the barrier timeout."""
    monkeypatch.setenv("SHIFU_TPU_FAULT", "dist.preempt_marker:oserror:1")
    resilience.reset_faults()
    resilience.publish_preempt("unit", process=0)   # must not raise
    assert resilience.check_preempt_marker() is None
    monkeypatch.delenv("SHIFU_TPU_FAULT")
    resilience.reset_faults()
    resilience.publish_preempt("unit", process=0)
    assert resilience.check_preempt_marker() is not None
