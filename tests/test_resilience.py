"""Resilience layer tests — retry/backoff classification, deterministic
fault injection (SHIFU_TPU_FAULT), atomic publication, per-step
manifests, and the crash/resume story end to end (SIGKILL a real
subprocess mid-step, restart, verify nothing corrupt and results match
an uninterrupted run)."""

import json
import logging
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from shifu_tpu import resilience
from shifu_tpu.data import fs as fs_mod


@pytest.fixture(autouse=True)
def _fresh_fault_counters():
    resilience.reset_faults()
    resilience.clear_preempt()
    resilience.set_abort_scope(None)
    yield
    resilience.reset_faults()
    resilience.clear_preempt()
    resilience.set_abort_scope(None)


# ---------------------------------------------------------------------------
# classification + fault-spec parsing units
# ---------------------------------------------------------------------------

def test_is_transient_classification():
    assert not resilience.is_transient(FileNotFoundError("gone"))
    assert not resilience.is_transient(PermissionError("denied"))
    assert not resilience.is_transient(IsADirectoryError("dir"))
    assert not resilience.is_transient(ValueError("bad value"))
    assert not resilience.is_transient(RuntimeError("no backend"))
    assert resilience.is_transient(TimeoutError("slow"))
    assert resilience.is_transient(ConnectionError("reset"))
    assert resilience.is_transient(OSError("flake"))

    class FSTimeoutError(Exception):  # fsspec's name, matched by name
        pass

    assert resilience.is_transient(FSTimeoutError("remote timeout"))


def test_fault_spec_parsing():
    rules = resilience._parse_fault_spec(
        "a.b:oserror:1; c:timeout:2-5,d:kill:3+")
    assert [(r.site, r.kind, r.lo, r.hi) for r in rules] == [
        ("a.b", "oserror", 1, 1),
        ("c", "timeout", 2, 5),
        ("d", "kill", 3, float("inf")),
    ]


@pytest.mark.parametrize("bad", ["a:oserror", "a:frobnicate:1",
                                 "a:oserror:x", "a:oserror:1-"])
def test_fault_spec_parsing_rejects(bad):
    with pytest.raises(ValueError, match="SHIFU_TPU_FAULT"):
        resilience._parse_fault_spec(bad)


def test_fault_point_counts_per_site(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_FAULT", "u.two:oserror:2;u.rng:timeout:1-2")
    resilience.fault_point("u.two")                       # call 1: ok
    with pytest.raises(OSError, match="injected oserror at u.two"):
        resilience.fault_point("u.two")                   # call 2: boom
    resilience.fault_point("u.two")                       # call 3: ok again
    for _ in range(2):                                    # range form
        with pytest.raises(TimeoutError, match="injected timeout"):
            resilience.fault_point("u.rng")
    resilience.fault_point("u.rng")                       # call 3: past range
    resilience.fault_point("u.unlisted")                  # other sites: no-op


# ---------------------------------------------------------------------------
# retry loop
# ---------------------------------------------------------------------------

def test_retry_recovers_from_injected_transient(monkeypatch, caplog):
    monkeypatch.setenv("SHIFU_TPU_FAULT", "u.once:oserror:1")
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.001")
    calls = {"n": 0}

    def work():
        calls["n"] += 1
        return "ok"

    with caplog.at_level(logging.WARNING, logger="shifu_tpu"):
        assert resilience.retrying("u.once", work) == "ok"
    assert calls["n"] == 1  # fault fired before attempt 1 reached work
    assert any("u.once" in r.getMessage() and "retrying" in r.getMessage()
               for r in caplog.records)


def test_retry_gives_up_after_budget(monkeypatch, caplog):
    monkeypatch.setenv("SHIFU_TPU_FAULT", "u.always:timeout:1+")
    monkeypatch.setenv("SHIFU_TPU_RETRY_ATTEMPTS", "3")
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.001")
    with caplog.at_level(logging.WARNING, logger="shifu_tpu"):
        with pytest.raises(TimeoutError, match="injected timeout"):
            resilience.retrying("u.always", lambda: "never")
    # observable: attempts-1 retry warnings, then the re-raise
    retries = [r for r in caplog.records if "retrying" in r.getMessage()]
    assert len(retries) == 2


def test_permanent_errors_not_retried(monkeypatch):
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.001")
    calls = {"n": 0}

    def missing():
        calls["n"] += 1
        raise FileNotFoundError("really gone")

    with pytest.raises(FileNotFoundError):
        resilience.retrying("u.perm", missing)
    assert calls["n"] == 1


def test_missing_backend_is_permanent():
    # unknown scheme → RuntimeError naming the missing backend, raised
    # immediately (no retry sleeps — the test finishing fast IS the
    # assertion that nothing backed off)
    with pytest.raises(RuntimeError, match="backend"):
        fs_mod.exists("no-such-scheme-zz://bucket/key")


def test_remote_fs_flake_retried_through_real_call(monkeypatch, caplog):
    """An injected flake on the instrumented fs.exists site is retried
    and the memory:// call then succeeds — the end-to-end remote-FS
    retry path."""
    fsspec = pytest.importorskip("fsspec")
    mem = fsspec.filesystem("memory")
    with mem.open("/resil/a.txt", "w") as f:
        f.write("hi")
    monkeypatch.setenv("SHIFU_TPU_FAULT", "fs.exists:oserror:1")
    monkeypatch.setenv("SHIFU_TPU_RETRY_BASE_S", "0.001")
    with caplog.at_level(logging.WARNING, logger="shifu_tpu"):
        assert fs_mod.exists("memory://resil/a.txt")
    assert any("fs.exists" in r.getMessage() and "retrying" in r.getMessage()
               for r in caplog.records)
    # and a permanently-missing file still reports False, not an error
    assert not fs_mod.exists("memory://resil/never-written.txt")


# ---------------------------------------------------------------------------
# atomic publication
# ---------------------------------------------------------------------------

def test_atomic_write_publishes_on_success(tmp_path):
    p = str(tmp_path / "out.json")
    with resilience.atomic_write(p) as f:
        json.dump({"ok": 1}, f)
    assert json.load(open(p)) == {"ok": 1}
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp.")]


def test_atomic_write_failure_preserves_old_content(tmp_path):
    p = str(tmp_path / "out.json")
    with open(p, "w") as f:
        f.write('{"old": true}')
    with pytest.raises(RuntimeError):
        with resilience.atomic_write(p) as f:
            f.write('{"new": tru')  # partial...
            raise RuntimeError("writer died")
    assert json.load(open(p)) == {"old": True}
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp.")]


def test_atomic_path_keeps_extension_for_numpy(tmp_path):
    p = str(tmp_path / "arr.npz")
    with resilience.atomic_path(p) as tmp:
        assert tmp.endswith(".npz")  # savez must not append a 2nd one
        np.savez(tmp, a=np.arange(3))
    with np.load(p) as z:
        np.testing.assert_array_equal(z["a"], np.arange(3))


def test_atomic_path_replaces_directory_target(tmp_path):
    target = tmp_path / "bundle"
    target.mkdir()
    (target / "stale.txt").write_text("old")
    with resilience.atomic_path(str(target)) as tmp:
        os.makedirs(tmp)
        with open(os.path.join(tmp, "fresh.txt"), "w") as f:
            f.write("new")
    assert sorted(os.listdir(target)) == ["fresh.txt"]


def test_atomic_file_explicit_commit(tmp_path):
    p = str(tmp_path / "scores.csv")
    f = resilience.AtomicFile(p)
    f.write("a,b\n")
    f.close(commit=False)  # failed streaming run: nothing published
    assert not os.path.exists(p)
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp.")]
    f = resilience.AtomicFile(p)
    f.write("a,b\n1,2\n")
    f.close(commit=True)
    assert open(p).read() == "a,b\n1,2\n"


def test_sweep_stale_tmp(tmp_path):
    (tmp_path / ".tmp.123.dead.npz").write_text("junk")
    os.makedirs(tmp_path / ".tmp.456.deaddir")
    (tmp_path / "keep.txt").write_text("keep")
    assert resilience.sweep_stale_tmp(str(tmp_path)) == 2
    assert sorted(os.listdir(tmp_path)) == ["keep.txt"]


# ---------------------------------------------------------------------------
# step manifests: resume-skip + invalidation
# ---------------------------------------------------------------------------

def test_step_manifest_skip_and_invalidation(tmp_path, rng, monkeypatch,
                                             caplog):
    from shifu_tpu.cli import main as cli_main
    from tests.synth import make_model_set

    root = make_model_set(tmp_path, rng, n_rows=300)
    assert cli_main(["--dir", root, "init"]) == 0
    assert cli_main(["--dir", root, "stats"]) == 0
    man = os.path.join(root, "tmp", "manifests", "stats.json")
    assert os.path.exists(man), "completed step must leave a manifest"
    cc_path = os.path.join(root, "ColumnConfig.json")
    cc_before = open(cc_path).read()

    # default (no SHIFU_TPU_RESUME): a re-run recomputes — manifest is
    # removed at entry and rewritten at exit, never consulted
    # opt-in resume: matching manifest + outputs present → skip
    monkeypatch.setenv("SHIFU_TPU_RESUME", "1")
    with caplog.at_level(logging.INFO, logger="shifu_tpu"):
        assert cli_main(["--dir", root, "stats"]) == 0
    assert any("skipping" in r.getMessage() for r in caplog.records)
    assert open(cc_path).read() == cc_before

    # changing an input invalidates the fingerprint → step re-runs
    mc_path = os.path.join(root, "ModelConfig.json")
    with open(mc_path, "a") as f:
        f.write("\n")  # still valid JSON, different bytes
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="shifu_tpu"):
        assert cli_main(["--dir", root, "stats"]) == 0
    assert any("re-running" in r.getMessage() for r in caplog.records)
    assert not any("skipping" in r.getMessage() for r in caplog.records)


# ---------------------------------------------------------------------------
# kill tests — a real SIGKILL in a subprocess, then verify no corruption
# ---------------------------------------------------------------------------

def _run_cli(root, cmd, extra_env=None, timeout=300):
    env = dict(os.environ)
    env.pop("SHIFU_TPU_FAULT", None)
    env.update(extra_env or {})
    code = ("import sys; from shifu_tpu.cli import main; "
            f"sys.exit(main(['--dir', {root!r}, {cmd!r}]))")
    return subprocess.run([sys.executable, "-c", code], env=env,
                          cwd="/root/repo", timeout=timeout,
                          capture_output=True, text=True)


def test_killed_step_leaves_no_corrupt_output(tmp_path, rng):
    """SIGKILL inside stats (mid ColumnConfig publish) and inside norm
    (mid output write): the prior outputs stay intact byte-for-byte, no
    completion manifest appears, and a clean re-run succeeds."""
    from shifu_tpu.cli import main as cli_main
    from tests.synth import make_model_set

    root = make_model_set(tmp_path, rng, n_rows=300)
    assert cli_main(["--dir", root, "init"]) == 0
    cc_path = os.path.join(root, "ColumnConfig.json")
    cc_init = open(cc_path).read()

    # stats killed at its first atomic commit (the ColumnConfig write)
    r = _run_cli(root, "stats",
                 extra_env={"SHIFU_TPU_FAULT": "atomic.commit:kill:1"})
    assert r.returncode == -signal.SIGKILL, r.stderr[-2000:]
    assert open(cc_path).read() == cc_init, \
        "killed stats step must not touch the published ColumnConfig"
    assert not os.path.exists(
        os.path.join(root, "tmp", "manifests", "stats.json"))
    assert cli_main(["--dir", root, "stats"]) == 0  # clean restart

    # norm killed at its first atomic commit (normalized block write) —
    # meta.json is written LAST, so readers never see a half layout
    norm_dir = os.path.join(root, "tmp", "NormalizedData")
    r = _run_cli(root, "norm",
                 extra_env={"SHIFU_TPU_FAULT": "atomic.commit:kill:1"})
    assert r.returncode == -signal.SIGKILL, r.stderr[-2000:]
    assert not os.path.exists(os.path.join(norm_dir, "meta.json"))
    assert not os.path.exists(
        os.path.join(root, "tmp", "manifests", "norm.json"))
    assert cli_main(["--dir", root, "norm"]) == 0
    assert os.path.exists(os.path.join(norm_dir, "meta.json"))
    with np.load(os.path.join(norm_dir, "data.npz")) as z:
        assert z.files  # published archive is readable


_TRAIN_SCRIPT = """\
import sys
import numpy as np
from shifu_tpu.config.model_config import ModelTrainConf
from shifu_tpu.train.trainer import train_nn

rng = np.random.default_rng(5)
x = rng.normal(0, 1, (400, 4)).astype(np.float32)
y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
w = np.ones(400, np.float32)
conf = ModelTrainConf.from_dict({
    "numTrainEpochs": 12, "baggingNum": 1, "validSetRate": 0.2,
    "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [6],
               "ActivationFunc": ["tanh"], "LearningRate": 0.1,
               "Propagation": "ADAM"}})
res = train_nn(conf, x, y, w, seed=7, checkpoint_dir=sys.argv[1],
               checkpoint_interval=4)
print("BEST_VAL", ",".join(repr(float(v)) for v in np.ravel(res.best_val)))
"""


def test_train_sigkill_then_resume_matches_uninterrupted(tmp_path):
    """Kill training with SIGKILL right after the 2nd checkpoint lands
    (SHIFU_TPU_FAULT=ckpt.saved:kill:2), restart, and the resumed run
    finishes with the same final validation metric as an uninterrupted
    run — the orbax-checkpoint crash/resume contract end to end."""
    from shifu_tpu.config.model_config import ModelTrainConf
    from shifu_tpu.train import checkpoint as ckpt
    from shifu_tpu.train.trainer import train_nn

    ckdir = str(tmp_path / "ck")
    env = dict(os.environ)
    env.pop("SHIFU_TPU_FAULT", None)

    killed = subprocess.run(
        [sys.executable, "-c", _TRAIN_SCRIPT, ckdir],
        env={**env, "SHIFU_TPU_FAULT": "ckpt.saved:kill:2",
             "SHIFU_TPU_CKPT_ASYNC": "1"},   # kill lands on the writer
        cwd="/root/repo", timeout=600, capture_output=True, text=True)
    assert killed.returncode == -signal.SIGKILL, killed.stderr[-2000:]
    assert ckpt.latest_step(ckdir) == 8, \
        "2nd published checkpoint (epoch 8) should have survived the kill"

    resumed = subprocess.run(
        [sys.executable, "-c", _TRAIN_SCRIPT, ckdir],
        env=env, cwd="/root/repo", timeout=600,
        capture_output=True, text=True)
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    line = [ln for ln in resumed.stdout.splitlines()
            if ln.startswith("BEST_VAL ")][0]
    resumed_best = np.array([float(v) for v in line.split(" ", 1)[1]
                             .split(",")])

    # uninterrupted reference run with the same data/conf/seed
    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (400, 4)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    w = np.ones(400, np.float32)
    conf = ModelTrainConf.from_dict({
        "numTrainEpochs": 12, "baggingNum": 1, "validSetRate": 0.2,
        "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [6],
                   "ActivationFunc": ["tanh"], "LearningRate": 0.1,
                   "Propagation": "ADAM"}})
    straight = train_nn(conf, x, y, w, seed=7)
    np.testing.assert_allclose(resumed_best, np.ravel(straight.best_val),
                               rtol=1e-4)


# ---------------------------------------------------------------------------
# preemption-safe shutdown + supervised restarts
# ---------------------------------------------------------------------------

def test_preempt_fault_kind_sets_flag(monkeypatch):
    """kind=preempt does NOT raise — it sets the graceful-shutdown flag
    exactly like the SIGTERM handler, so epoch loops stop at their next
    step boundary."""
    monkeypatch.setenv("SHIFU_TPU_FAULT", "x.site:preempt:2")
    resilience.fault_point("x.site")
    assert not resilience.preempt_requested()
    resilience.fault_point("x.site")
    assert resilience.preempt_requested()


def test_graceful_shutdown_signal_flow():
    """First SIGTERM sets the flag (no exception mid-step); a second
    signal escalates to KeyboardInterrupt; handlers restore on exit."""
    import time

    prev_term = signal.getsignal(signal.SIGTERM)
    with resilience.graceful_shutdown("test"):
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.01)   # let the interpreter deliver it
        assert resilience.preempt_requested()
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.05)
    assert signal.getsignal(signal.SIGTERM) is prev_term
    resilience.clear_preempt()


def test_supervise_restarts_on_preempt_and_transient():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise resilience.Preempted("p")
        if len(calls) == 2:
            raise TimeoutError("transient")
        return "ok"

    os.environ["SHIFU_TPU_MAX_RESTARTS"] = "3"
    try:
        assert resilience.supervise(flaky, step="t") == "ok"
    finally:
        del os.environ["SHIFU_TPU_MAX_RESTARTS"]
    assert len(calls) == 3


def test_supervise_permanent_error_and_exhausted_budget():
    def bad():
        raise ValueError("permanent")

    os.environ["SHIFU_TPU_MAX_RESTARTS"] = "5"
    try:
        with pytest.raises(ValueError):
            resilience.supervise(bad, step="t")

        n = []

        def always_preempted():
            n.append(1)
            raise resilience.Preempted("again")

        with pytest.raises(resilience.Preempted):
            os.environ["SHIFU_TPU_MAX_RESTARTS"] = "2"
            resilience.supervise(always_preempted, step="t")
        assert len(n) == 3   # 1 try + 2 restarts
    finally:
        del os.environ["SHIFU_TPU_MAX_RESTARTS"]


def test_supervise_off_by_default():
    n = []

    def once():
        n.append(1)
        raise resilience.Preempted("p")

    with pytest.raises(resilience.Preempted):
        resilience.supervise(once, step="t")
    assert len(n) == 1


@pytest.mark.parametrize("ckpt_async", ["0", "1"])
def test_preempt_supervised_resume_matches_uninterrupted(tmp_path,
                                                         monkeypatch,
                                                         ckpt_async):
    """The acceptance run: inject a preemption notice right after the
    first checkpoint lands; training raises Preempted, the supervisor
    re-invokes, the trainer restores at the checkpointed epoch and
    finishes — with the SAME final validation metric as an
    uninterrupted run. Parametrized over the background checkpoint
    writer (ISSUE-5: preempt-then-resume under async must match sync
    and the uninterrupted run)."""
    from shifu_tpu.config.model_config import ModelTrainConf
    from shifu_tpu.train.trainer import train_nn

    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (400, 4)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    w = np.ones(400, np.float32)
    conf = ModelTrainConf.from_dict({
        "numTrainEpochs": 12, "baggingNum": 1, "validSetRate": 0.2,
        "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [6],
                   "ActivationFunc": ["tanh"], "LearningRate": 0.1,
                   "Propagation": "ADAM"}})
    ckdir = str(tmp_path / "ck")

    monkeypatch.setenv("SHIFU_TPU_CKPT_ASYNC", ckpt_async)
    monkeypatch.setenv("SHIFU_TPU_FAULT", "ckpt.saved:preempt:1")
    monkeypatch.setenv("SHIFU_TPU_MAX_RESTARTS", "2")
    resilience.reset_faults()
    attempts = []

    def attempt():
        attempts.append(1)
        return train_nn(conf, x, y, w, seed=7, checkpoint_dir=ckdir,
                        checkpoint_interval=4)

    res = resilience.supervise(attempt, step="train")
    assert len(attempts) == 2, "preemption should trigger one restart"

    monkeypatch.delenv("SHIFU_TPU_FAULT")
    straight = train_nn(conf, x, y, w, seed=7)
    np.testing.assert_allclose(np.ravel(res.best_val),
                               np.ravel(straight.best_val), rtol=1e-4)


# ---------------------------------------------------------------------------
# abort markers (poison barriers) + collective watchdog
# ---------------------------------------------------------------------------

def test_abort_marker_roundtrip(tmp_path):
    resilience.set_abort_scope(str(tmp_path))
    assert resilience.check_abort() is None
    resilience.publish_abort("psi", RuntimeError("boom"), process=2)
    ab = resilience.check_abort()
    assert ab["site"] == "psi" and ab["process"] == 2
    assert "RuntimeError: boom" in ab["error"]
    resilience.clear_abort()
    assert resilience.check_abort() is None


def test_abort_marker_remote_twin(tmp_path):
    fsspec = pytest.importorskip("fsspec")
    from fsspec.implementations.memory import MemoryFileSystem

    MemoryFileSystem.store.clear()
    resilience.set_abort_scope("memory://abortscope")
    try:
        assert resilience.check_abort() is None
        resilience.publish_abort("norm", OSError("remote boom"), process=1)
        ab = resilience.check_abort()
        assert ab["process"] == 1 and "remote boom" in ab["error"]
        # the marker committed via the atomic remote twin: no dot-temp
        # residue under the scope
        fs = fsspec.filesystem("memory")
        names = [n.rpartition("/")[2] for n in fs.ls("/abortscope", detail=False)]
        assert not [n for n in names if n.startswith(".tmp.")]
        resilience.clear_abort()
        assert resilience.check_abort() is None
    finally:
        MemoryFileSystem.store.clear()


def test_watchdog_times_out_and_dumps_stacks(tmp_path, monkeypatch,
                                             capsys):
    import time

    from shifu_tpu.parallel import dist

    monkeypatch.setenv("SHIFU_TPU_BARRIER_TIMEOUT_S", "0.4")
    t0 = time.monotonic()
    with pytest.raises(dist.DistTimeout):
        dist._watched("unit", lambda: time.sleep(60))
    assert time.monotonic() - t0 < 10
    err = capsys.readouterr().err
    assert "thread stacks" in err and "unit" in err


def test_watchdog_poisoned_by_peer_abort(tmp_path, monkeypatch):
    import time

    from shifu_tpu.parallel import dist

    resilience.set_abort_scope(str(tmp_path))
    resilience.publish_abort("stats", RuntimeError("peer died"),
                             process=1)
    # no timeout set: the abort marker alone must unblock the wait
    monkeypatch.delenv("SHIFU_TPU_BARRIER_TIMEOUT_S", raising=False)
    with pytest.raises(dist.DistAborted, match="peer died"):
        dist._watched("unit", lambda: time.sleep(60))


def test_watchdog_passes_value_and_error_through(monkeypatch):
    from shifu_tpu.parallel import dist

    monkeypatch.setenv("SHIFU_TPU_BARRIER_TIMEOUT_S", "5")
    assert dist._watched("v", lambda: 41 + 1) == 42
    with pytest.raises(RuntimeError, match="organic"):
        dist._watched("e", _raise_organic)


def _raise_organic():
    raise RuntimeError("organic")


def test_single_writer_publishes_abort_when_multiprocess(tmp_path,
                                                         monkeypatch):
    """When a single_writer body raises in a (simulated) multi-process
    run, an abort marker lands under the scope before the error
    propagates."""
    from shifu_tpu.parallel import dist

    resilience.set_abort_scope(str(tmp_path))
    monkeypatch.setattr(dist, "_multi_process", lambda: True)
    monkeypatch.setattr(dist.jax, "process_count", lambda: 2)
    monkeypatch.setattr(dist.jax, "process_index", lambda: 0)
    # the release barrier would block on sync_global_devices; the
    # marker from OUR OWN process must not poison it, so stub the
    # collective itself
    monkeypatch.setattr(dist, "_watched", lambda tag, fn: None)
    with pytest.raises(RuntimeError, match="writer exploded"):
        with dist.single_writer("unit") as w:
            assert w
            raise RuntimeError("writer exploded")
    ab = resilience.check_abort()
    assert ab is not None and "writer exploded" in ab["error"]
    # ...and a DIFFERENT process polling the same scope aborts with
    # that error
    monkeypatch.setattr(dist.jax, "process_index", lambda: 1)
    with pytest.raises(dist.DistAborted, match="writer exploded"):
        dist.writer_barrier("unit")


# ---------------------------------------------------------------------------
# remote sweep twin
# ---------------------------------------------------------------------------

def test_sweep_stale_tmp_remote(tmp_path):
    fsspec = pytest.importorskip("fsspec")
    from fsspec.implementations.memory import MemoryFileSystem

    MemoryFileSystem.store.clear()
    fs = fsspec.filesystem("memory")
    try:
        with fs.open("/out/.tmp.123.part-0.csv", "w") as f:
            f.write("orphaned")
        with fs.open("/out/.tmp.456.meta.json", "w") as f:
            f.write("orphaned")
        with fs.open("/out/part-0.csv", "w") as f:
            f.write("real")
        assert resilience.sweep_stale_tmp_remote("memory://out") == 2
        names = [n.rpartition("/")[2] for n in fs.ls("/out", detail=False)]
        assert names == ["part-0.csv"]
        # idempotent + missing dir tolerated
        assert resilience.sweep_stale_tmp_remote("memory://out") == 0
        assert resilience.sweep_stale_tmp_remote("memory://nothere") == 0
        # the dispatcher routes by scheme
        assert resilience.sweep_stale("memory://out") == 0
        local = tmp_path / "d"
        local.mkdir()
        (local / ".tmp.9.x").write_text("junk")
        assert resilience.sweep_stale(str(local)) == 1
    finally:
        MemoryFileSystem.store.clear()
