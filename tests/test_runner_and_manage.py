"""ModelRunner embedding API, encode, manage, eval-norm, upsample."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.cli import main as cli_main
from shifu_tpu.processor.base import ProcessorContext


@pytest.fixture()
def trained(model_set):
    for cmd in (["init"], ["stats"], ["norm"], ["train"]):
        assert cli_main(["--dir", model_set] + cmd) == 0
    return model_set


def test_model_runner_single_record(trained):
    from shifu_tpu.eval.model_runner import ModelRunner
    runner = ModelRunner.from_model_set(trained)
    rec = {"num_0": "1.2", "num_1": "0.1", "num_2": "2.0", "num_3": "-0.5",
           "num_4": "1.5", "num_5": "0.3", "cat_0": "aa", "cat_1": "bb",
           "wgt": "1.0", "rowid": "x"}
    result = runner.compute(rec)
    assert 0.0 <= result.avg_score <= 1.0
    assert result.max_score >= result.avg_score >= result.min_score
    # positive-leaning record (high num_0..4, cat 'aa') scores higher than
    # a negative-leaning one
    neg = dict(rec, num_0="-2", num_2="-2", num_4="-2", cat_0="dd", cat_1="dd")
    assert runner.compute(rec).avg_score > runner.compute(neg).avg_score


def test_model_runner_missing_columns(trained):
    """Records lacking some feature columns still score (missing
    treatment, like ModelRunner's map path)."""
    from shifu_tpu.eval.model_runner import ModelRunner
    runner = ModelRunner.from_model_set(trained)
    result = runner.compute({"num_0": "1.0", "cat_0": "aa"})
    assert 0.0 <= result.avg_score <= 1.0


def test_model_runner_delimited_string(trained):
    from shifu_tpu.eval.model_runner import ModelRunner
    runner = ModelRunner.from_model_set(trained)
    header = runner.header
    values = {"num_0": "1.2", "num_1": "0", "num_2": "1", "num_3": "0",
              "num_4": "1", "num_5": "0", "cat_0": "aa", "cat_1": "aa",
              "wgt": "1", "rowid": "1", "diagnosis": "M"}
    line = "|".join(values.get(h, "") for h in header)
    result = runner.compute(line)
    assert 0.0 <= result.avg_score <= 1.0


def test_eval_norm_export(trained):
    assert cli_main(["--dir", trained, "eval", "-norm"]) == 0
    ctx = ProcessorContext.load(trained)
    path = ctx.path_finder.eval_norm_path("Eval1")
    lines = open(path).read().splitlines()
    assert lines[0].startswith("tag,weight,")
    assert len(lines) > 100


def test_encode_requires_tree_model(trained):
    assert cli_main(["--dir", trained, "encode"]) == 1  # NN trained, no tree


def test_encode_with_gbt(tmp_path, rng):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=800, algorithm="GBT",
                          train_params={"TreeNum": 4, "MaxDepth": 3,
                                        "LearningRate": 0.3, "Loss": "log"})
    for cmd in (["init"], ["stats"], ["norm"], ["train"], ["encode"]):
        assert cli_main(["--dir", root] + cmd) == 0
    enc = os.path.join(root, "encoded")
    header = open(os.path.join(enc, ".pig_header")).read().strip().split("|")
    assert header == ["tag", "weight", "tree_0", "tree_1", "tree_2", "tree_3"]
    rows = open(os.path.join(enc, "part-00000")).read().splitlines()
    assert len(rows) == 640  # synth splits 80% into the train dir
    leaf = int(rows[0].split("|")[2])
    assert leaf >= 3  # landed at depth ≥ 1 (beyond root region)


def test_manage_save_switch_show(trained):
    assert cli_main(["--dir", trained, "save", "v1"]) == 0
    # mutate: deselect everything
    ctx = ProcessorContext.load(trained)
    for cc in ctx.column_configs:
        cc.finalSelect = False
    ctx.save_column_configs()
    assert cli_main(["--dir", trained, "switch", "v1"]) == 0
    ctx = ProcessorContext.load(trained)
    # v1 had no finalSelect either (train before varsel), but models/ restored
    assert os.path.exists(ctx.path_finder.model_path(0, "nn"))
    assert cli_main(["--dir", trained, "show"]) == 0
    from shifu_tpu.processor import manage
    assert set(manage.list_versions(ctx)) == {"v1", "master"}
    # switching to a nonexistent version errors cleanly
    assert cli_main(["--dir", trained, "switch", "nope"]) == 1


def test_upsample_weight_changes_training(rng, tmp_path):
    from tests.synth import make_model_set
    from shifu_tpu.processor import (init as init_proc, stats as stats_proc,
                                     norm as norm_proc, train as train_proc)
    root = make_model_set(tmp_path, rng, n_rows=800)
    for proc in (init_proc, stats_proc, norm_proc):
        ctx = ProcessorContext.load(root)
        proc.run(ctx)
    ctx = ProcessorContext.load(root)
    train_proc.run(ctx)
    from shifu_tpu.models.spec import load_model
    _, _, p1 = load_model(ctx.path_finder.model_path(0, "nn"))
    ctx = ProcessorContext.load(root)
    ctx.model_config.train.upSampleWeight = 5.0
    train_proc.run(ctx)
    _, _, p2 = load_model(ctx.path_finder.model_path(0, "nn"))
    assert not np.allclose(p1[0]["w"], p2[0]["w"])


def test_eval_norm_chunked_matches(trained, monkeypatch):
    """eval -norm output is identical for any chunking (row-local
    normalization; >RAM sets export with bounded memory)."""
    assert cli_main(["--dir", trained, "eval", "-norm"]) == 0
    ctx = ProcessorContext.load(trained)
    path = ctx.path_finder.eval_norm_path("Eval1")
    whole = open(path).read()
    monkeypatch.setenv("SHIFU_TPU_EVAL_CHUNK_ROWS", "97")
    assert cli_main(["--dir", trained, "eval", "-norm"]) == 0
    assert open(path).read() == whole
