"""Low-latency serving plane (shifu_tpu/serve/).

The serving contract has three legs, each tested here:

- PARITY: served scores bit-match batch eval (`Scorer.score` on the
  whole block) for NN and GBT, and agree with the portable / PMML
  external evaluators within their usual tolerances — padding up the
  shape-bucket ladder and micro-batch merging must be invisible.
- BATCHING: the micro-batcher flushes on bucket fill OR the opener's
  deadline, preserves FIFO order through overflow carry, rejects on a
  full admission queue, and surfaces injected `serve.request` faults
  to exactly one caller.
- WARM START: after `start()` warms every bucket, steady-state ragged
  traffic takes zero compile-cache misses (the "never recompiles"
  acceptance gate), and chunked batch eval routed through the same
  pad helper scores identically with padding on or off.
"""

import json
import os
import queue
import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from shifu_tpu import resilience
from shifu_tpu.cli import main as cli_main
from shifu_tpu.data import pipeline
from shifu_tpu.processor.base import ProcessorContext
from shifu_tpu.serve import aot
from shifu_tpu.serve.batcher import MicroBatcher


@pytest.fixture(autouse=True)
def _no_faults():
    resilience.reset_faults()
    yield
    resilience.reset_faults()


def _pipeline(model_set, *extra):
    for cmd in (["init"], ["stats"], ["norm"], ["train"], *extra):
        assert cli_main(["--dir", model_set] + list(cmd)) == 0
    return model_set


def _norm_blocks(root):
    from shifu_tpu.processor import norm as norm_proc
    ctx = ProcessorContext.load(root)
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    return ctx, data, meta


def _tiny_nn_dir(root, input_dim=12, seed=0):
    """A throwaway single-NN model dir (no training) for batcher /
    service plumbing tests — the parity tests use real pipelines."""
    import jax

    from shifu_tpu.models import nn as nn_mod
    from shifu_tpu.models.spec import save_model
    os.makedirs(root, exist_ok=True)
    spec = nn_mod.MLPSpec(input_dim=input_dim, hidden_dims=(8,),
                          activations=("relu",))
    params = nn_mod.init_params(spec, jax.random.PRNGKey(seed))
    save_model(os.path.join(root, "model0.npz"), "nn",
               {"spec": {"input_dim": input_dim, "hidden_dims": [8],
                         "activations": ["relu"]}},
               jax.tree.map(np.asarray, params))
    return root


def _ragged_pieces(n, sizes=(3, 1, 7, 5, 2)):
    """Split [0, n) into uneven request-sized pieces."""
    out, off, i = [], 0, 0
    while off < n:
        step = min(sizes[i % len(sizes)], n - off)
        out.append((off, off + step))
        off += step
        i += 1
    return out


# ---------------------------------------------------------------------------
# Shape buckets + padding
# ---------------------------------------------------------------------------

def test_bucket_ladder_and_padding(monkeypatch):
    assert aot.bucket_for(1, (1, 8, 64)) == 1
    assert aot.bucket_for(2, (1, 8, 64)) == 8
    assert aot.bucket_for(64, (1, 8, 64)) == 64
    assert aot.bucket_for(65, (1, 8, 64)) == 128    # top rung doubles
    assert aot.bucket_for(300, (1, 8, 64)) == 512
    with pytest.raises(ValueError):
        aot.bucket_for(0)

    block = np.arange(6, dtype=np.float32).reshape(3, 2)
    padded = aot.pad_rows(block, 8)
    assert padded.shape == (8, 2)
    np.testing.assert_array_equal(padded[:3], block)
    np.testing.assert_array_equal(padded[3:],
                                  np.repeat(block[-1:], 5, axis=0))
    with pytest.raises(ValueError):
        aot.pad_rows(block, 2)

    monkeypatch.setenv("SHIFU_TPU_SERVE_BUCKETS", "16,4,256")
    assert aot.bucket_ladder() == (4, 16, 256)      # sorted + deduped
    monkeypatch.setenv("SHIFU_TPU_SERVE_BUCKETS", "banana")
    assert aot.bucket_ladder() == aot.DEFAULT_LADDER


def test_padded_call_slices_back(tmp_path):
    """Within a bucket, padding is bit-invisible; vs an unpadded call
    at a different shape, results agree to XLA scheduling noise."""
    from shifu_tpu.eval.scorer import Scorer
    models = _tiny_nn_dir(str(tmp_path / "models"))
    scorer = Scorer.from_dir(models)
    x = np.random.default_rng(3).normal(0, 1, (11, 12)).astype(np.float32)

    padded = aot.padded_call(scorer.score, 11, {"dense": x},
                             ladder=(1, 8, 64))
    manual = {k: np.asarray(v)[:11]
              for k, v in scorer.score(aot.pad_rows(x, 64)).items()}
    for key in manual:                         # same bucket → bitwise
        np.testing.assert_array_equal(np.asarray(padded[key]),
                                      manual[key])

    direct = scorer.score(x)                   # different shape → ~1 ulp
    for key in manual:
        np.testing.assert_allclose(np.asarray(padded[key]),
                                   np.asarray(direct[key]),
                                   rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# Micro-batcher units
# ---------------------------------------------------------------------------

def _echo_batcher(max_rows, max_delay, depth=64):
    def score_batch(batch):
        for r in batch:
            r.resolve(r.blocks["x"] * 2.0)
    mb = MicroBatcher(score_batch, max_rows=max_rows,
                      max_delay=max_delay, depth=depth)
    mb.start()
    return mb


def test_batcher_deadline_flush():
    """A lone request is not held past the opener's deadline even when
    the bucket never fills."""
    mb = _echo_batcher(max_rows=512, max_delay=0.08)
    try:
        t0 = time.monotonic()
        req = mb.submit({"x": np.ones((2, 3), np.float32)}, 2)
        out = req.wait(10.0)
        waited = time.monotonic() - t0
        np.testing.assert_array_equal(out, np.full((2, 3), 2.0))
        assert waited < 5.0, "deadline flush did not fire"
        assert req.timing["queue_s"] >= 0.05, \
            "lone request should ride out the full admission window"
        assert mb.stats()["batches"] == 1
    finally:
        mb.close()


def test_batcher_bucket_fill_flushes_early():
    """Once queued rows reach the top bucket the batch launches without
    waiting for a (deliberately huge) deadline."""
    mb = _echo_batcher(max_rows=8, max_delay=30.0)
    try:
        t0 = time.monotonic()
        r1 = mb.submit({"x": np.ones((4, 2), np.float32)}, 4)
        r2 = mb.submit({"x": np.ones((4, 2), np.float32)}, 4)
        r1.wait(10.0)
        r2.wait(10.0)
        assert time.monotonic() - t0 < 10.0, \
            "full bucket waited for the deadline"
        s = mb.stats()
        assert s["batches"] == 1 and s["requests"] == 2 and s["rows"] == 8
    finally:
        mb.close()


def test_batcher_ordering_and_carry():
    """Each request gets exactly its own rows back; a co-rider that
    would overflow the bucket opens the NEXT batch (FIFO preserved)."""
    got = []

    def score_batch(batch):
        got.append([r.n for r in batch])
        off = 0
        for r in batch:
            r.resolve(r.blocks["x"] + 100.0)
            off += r.n

    mb = MicroBatcher(score_batch, max_rows=8, max_delay=0.2, depth=64)
    mb.start()
    try:
        reqs = [mb.submit({"x": np.full((4, 2), float(i), np.float32)}, 4)
                for i in range(3)]
        outs = [r.wait(10.0) for r in reqs]
        for i, out in enumerate(outs):
            np.testing.assert_array_equal(
                out, np.full((4, 2), 100.0 + i, np.float32))
        flat = [n for b in got for n in b]
        assert flat == [4, 4, 4], f"requests reordered/split: {got}"
        assert len(got) >= 2, "third request must overflow to batch 2"
    finally:
        mb.close()


def test_batcher_backpressure_and_close():
    """Bounded admission queue: overflow is a prompt `queue.Full`, and
    close() rejects stragglers instead of stranding them."""
    gate = threading.Event()

    def score_batch(batch):
        gate.wait(30.0)
        for r in batch:
            r.resolve(r.blocks["x"])

    mb = MicroBatcher(score_batch, max_rows=4, max_delay=0.01, depth=1)
    mb.start()
    try:
        first = mb.submit({"x": np.ones((4, 1), np.float32)}, 4)
        deadline = time.monotonic() + 10.0
        while mb.stats()["batches"] < 1:     # consumer holds `first`
            assert time.monotonic() < deadline
            time.sleep(0.005)
        second = mb.submit({"x": np.ones((4, 1), np.float32)}, 4)
        with pytest.raises(queue.Full):
            mb.submit({"x": np.ones((4, 1), np.float32)}, 4)
        gate.set()
        first.wait(10.0)
        second.wait(10.0)
    finally:
        gate.set()
        mb.close()
    with pytest.raises(RuntimeError):
        mb.submit({"x": np.ones((1, 1), np.float32)}, 1)


def test_batcher_row_bounds():
    mb = _echo_batcher(max_rows=8, max_delay=0.01)
    try:
        with pytest.raises(ValueError):
            mb.submit({"x": np.ones((9, 1), np.float32)}, 9)
        with pytest.raises(ValueError):
            mb.submit({"x": np.ones((1, 1), np.float32)}, 0)
    finally:
        mb.close()


# ---------------------------------------------------------------------------
# Service parity: served == batch eval == portable / PMML
# ---------------------------------------------------------------------------

def test_served_bitmatch_nn_and_portable(model_set):
    """A served request bit-matches batch eval scored at the same
    bucket (the padded path eval itself uses); ragged concurrent
    submits reassemble to the same scores up to XLA scheduling noise;
    and the numpy-only portable scorer agrees."""
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.portable import PortableScorer
    from shifu_tpu.serve.service import ScorerService

    _pipeline(model_set)
    ctx, data, meta = _norm_blocks(model_set)
    dense = np.asarray(data["dense"], np.float32)[:96]
    models = ctx.path_finder.models_path()
    scorer = Scorer.from_dir(models)
    batch = {k: np.asarray(v)
             for k, v in aot.padded_call(scorer.score, dense.shape[0],
                                         {"dense": dense}).items()}

    svc = ScorerService(models_dir=models, max_delay=0.005)
    with svc:
        # leg 1 — whole block as one request: same bucket as the
        # padded batch-eval call above, so every column is bitwise
        whole = svc.submit(dense=dense, timeout=60.0)
        for key in batch:
            np.testing.assert_array_equal(
                np.asarray(whole[key]), batch[key],
                err_msg=f"served {key} deviates from batch eval")

        # leg 2 — ragged concurrent submits: micro-batches land on
        # arrival-dependent buckets, bounded by scheduling noise
        pieces = _ragged_pieces(dense.shape[0])
        reqs = [None] * len(pieces)

        def submit(i, lo, hi):
            reqs[i] = svc.submit_async(dense=dense[lo:hi])

        threads = [threading.Thread(target=submit, args=(i, lo, hi))
                   for i, (lo, hi) in enumerate(pieces)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        outs = [r.wait(60.0) for r in reqs]

    for key in batch:
        served = np.concatenate([np.asarray(o[key]) for o in outs])
        np.testing.assert_allclose(
            served, batch[key], rtol=1e-6, atol=1e-7,
            err_msg=f"ragged served {key} deviates from batch eval")

    portable = PortableScorer(models).score(dense=dense)["mean"]
    np.testing.assert_allclose(np.asarray(whole["mean"]), portable,
                               rtol=1e-5, atol=1e-6)

    stats = svc.stats()
    assert stats["warmed_buckets"] == len(stats["ladder"])
    assert stats["aot_executables"] == len(stats["ladder"])  # 1 NN model


def test_served_bitmatch_gbt(tmp_path, rng):
    """Tree ensembles serve raw blocks; padding by repeating the last
    row cannot move any per-row tree walk, so RAW scores bit-match."""
    from tests.synth import make_model_set

    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.processor import norm as norm_proc
    from shifu_tpu.processor.norm import load_dataset_for_columns
    from shifu_tpu.serve.service import ScorerService

    root = make_model_set(tmp_path, rng, n_rows=1200, algorithm="GBT",
                          train_params={"TreeNum": 4, "MaxDepth": 3,
                                        "LearningRate": 0.1,
                                        "Loss": "squared"})
    _pipeline(root)
    ctx = ProcessorContext.load(root)
    cols = norm_proc.selected_candidates(ctx.column_configs)
    dset = load_dataset_for_columns(ctx.model_config, ctx.column_configs,
                                    cols)
    if dset.cat_codes.shape[1]:
        vlen = np.asarray([len(v) for v in dset.vocabs], np.int32)
        raw_codes = np.where(dset.cat_codes < 0, vlen[None, :],
                             dset.cat_codes).astype(np.int32)
    else:
        raw_codes = dset.cat_codes
    numeric = np.asarray(dset.numeric, np.float32)[:80]
    raw_codes = np.asarray(raw_codes)[:80]

    models = ctx.path_finder.models_path()
    scorer = Scorer.from_dir(models)
    blocks = {"raw_dense": numeric, "raw_codes": raw_codes}
    batch = aot.padded_call(
        lambda raw_dense=None, raw_codes=None: scorer.score(
            raw_dense, raw_dense=raw_dense, raw_codes=raw_codes),
        numeric.shape[0], blocks)["mean"]

    svc = ScorerService(models_dir=models, max_delay=0.005)
    with svc.start(proto={"raw_dense": numeric[:1],
                          "raw_codes": raw_codes[:1]}):
        whole = svc.submit(raw_dense=numeric, raw_codes=raw_codes,
                           timeout=60.0)
        np.testing.assert_array_equal(np.asarray(whole["mean"]),
                                      np.asarray(batch))
        outs = [svc.submit(raw_dense=numeric[lo:hi],
                           raw_codes=raw_codes[lo:hi], timeout=60.0)
                for lo, hi in _ragged_pieces(numeric.shape[0])]
    served = np.concatenate([np.asarray(o["mean"]) for o in outs])
    np.testing.assert_allclose(served, np.asarray(batch),
                               rtol=1e-6, atol=1e-7)


def test_served_matches_pmml_external_eval(model_set):
    """Scores served over the wire-facing path agree with the exported
    PMML document evaluated from RAW records — the cross-stack
    conformance gate, at the jpmml tolerances."""
    from shifu_tpu import pmml as pmml_mod
    from shifu_tpu.data.dataset import build_columnar
    from shifu_tpu.eval.model_runner import ModelRunner
    from shifu_tpu.processor import norm as norm_proc
    from shifu_tpu.serve.service import ScorerService
    from tests.test_portable_pmml import _raw_eval_frame

    _pipeline(model_set)
    assert cli_main(["--dir", model_set, "export", "-t", "pmml"]) == 0
    ctx = ProcessorContext.load(model_set)
    df = _raw_eval_frame(model_set).head(48)
    pmml_scores = pmml_mod.evaluate_pmml(
        open(ctx.path_finder.pmml_path(0)).read(), df.copy())

    # the exact preprocessing ModelRunner.score_frame applies
    runner = ModelRunner.from_model_set(model_set)
    frame = df.copy()
    for c in runner.cols:
        if c.columnName not in frame.columns:
            frame = frame.assign(**{c.columnName: ""})
    dset = build_columnar(
        runner.mc, norm_proc._restrict(runner.ccs, runner.cols),
        frame.astype(str),
        vocabs={c.columnNum: (c.columnBinning.binCategory or [])
                for c in runner.cols if c.is_categorical})
    result = norm_proc.normalize_columns(runner.mc, runner.cols, dset)
    dense = np.asarray(result.dense, np.float32)

    svc = ScorerService(models_dir=ctx.path_finder.models_path(),
                        max_delay=0.005)
    with svc:
        outs = [svc.submit(dense=dense[lo:hi], timeout=60.0)
                for lo, hi in _ragged_pieces(dense.shape[0])]
    served = np.concatenate([np.asarray(o["mean"]) for o in outs])

    want = runner.score_frame(df.copy())["mean"]   # unpadded shape
    np.testing.assert_allclose(served, np.asarray(want),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(served, pmml_scores, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Fault injection + schema guard
# ---------------------------------------------------------------------------

def test_serve_request_fault_hits_one_caller(tmp_path, monkeypatch):
    """`serve.request:oserror:1` fails exactly the first submit; the
    service stays healthy for the next one."""
    from shifu_tpu.serve.service import ScorerService

    models = _tiny_nn_dir(str(tmp_path / "models"))
    monkeypatch.setenv("SHIFU_TPU_FAULT", "serve.request:oserror:1")
    resilience.reset_faults()
    x = np.zeros((2, 12), np.float32)
    with ScorerService(models_dir=models, max_delay=0.005,
                       aot_compile=False) as svc:
        with pytest.raises(OSError):
            svc.submit(dense=x)
        out = svc.submit(dense=x, timeout=30.0)
        assert np.asarray(out["mean"]).shape == (2,)


def test_service_schema_guard(tmp_path):
    from shifu_tpu.serve.service import ScorerService

    models = _tiny_nn_dir(str(tmp_path / "models"))
    with ScorerService(models_dir=models, max_delay=0.005,
                       aot_compile=False) as svc:
        svc.submit(dense=np.zeros((1, 12), np.float32), timeout=30.0)
        with pytest.raises(ValueError):        # schema mismatch
            svc.submit(dense=np.zeros((1, 12), np.float32),
                       raw_dense=np.zeros((1, 12), np.float32))
        with pytest.raises(ValueError):        # row-count disagreement
            svc.submit_async(dense=np.zeros((2, 12), np.float32),
                             index=np.zeros((3, 1), np.int32))
        with pytest.raises(ValueError):        # no blocks at all
            svc.submit_async()


# ---------------------------------------------------------------------------
# AOT warm start: steady state never recompiles
# ---------------------------------------------------------------------------

def test_warm_start_zero_steady_state_cache_misses(tmp_path):
    """After a second service start against the same workspace warms
    every bucket, ragged traffic triggers ZERO compile-cache misses —
    the core latency guarantee of the AOT layer."""
    from shifu_tpu.serve.service import ScorerService

    models = _tiny_nn_dir(str(tmp_path / "models"))
    ws = str(tmp_path / "ws")

    with ScorerService(models_dir=models, workspace_root=ws,
                       max_delay=0.005) as svc:
        svc.submit(dense=np.zeros((3, 12), np.float32), timeout=30.0)

    # second start of the same service shape: warm-up repopulates the
    # in-process caches (reading the persistent cache where eligible)
    svc = ScorerService(models_dir=models, workspace_root=ws,
                        max_delay=0.005)
    with svc:
        pipeline.drain_stage_timers()          # discard warm-up compiles
        rng = np.random.default_rng(0)
        for n in (1, 3, 7, 8, 13, 64, 100, 512):
            out = svc.submit(
                dense=rng.normal(0, 1, (n, 12)).astype(np.float32),
                timeout=60.0)
            assert np.asarray(out["mean"]).shape == (n,)
        steady = pipeline.drain_stage_timers()

    assert steady.get("compile_cache_misses", 0) == 0, \
        f"steady-state traffic recompiled: {steady}"
    assert steady.get("serve_batches", 0) >= 1
    assert steady.get("serve_device_s", 0) > 0


# ---------------------------------------------------------------------------
# Batch eval rides the same pad helper
# ---------------------------------------------------------------------------

def test_eval_pad_buckets_score_parity(model_set, monkeypatch):
    """Chunked `shifu eval` with SHIFU_TPU_EVAL_PAD_BUCKETS on vs off
    scores every row identically (up to the ~1-ulp XLA scheduling
    noise a shape change can introduce, far below the %.6f the score
    file carries) — bucket padding is a compile-count optimization,
    not a numerics change."""
    _pipeline(model_set)
    ctx = ProcessorContext.load(model_set)
    score_path = ctx.path_finder.eval_score_path("Eval1")
    monkeypatch.setenv("SHIFU_TPU_EVAL_CHUNK_ROWS", "96")  # ragged tail

    monkeypatch.setenv("SHIFU_TPU_EVAL_PAD_BUCKETS", "0")
    assert cli_main(["--dir", model_set, "eval"]) == 0
    unpadded = pd.read_csv(score_path)

    monkeypatch.setenv("SHIFU_TPU_EVAL_PAD_BUCKETS", "1")
    assert cli_main(["--dir", model_set, "eval"]) == 0
    padded = pd.read_csv(score_path)

    assert list(padded.columns) == list(unpadded.columns)
    assert len(padded) == len(unpadded)
    for col in padded.columns:
        if padded[col].dtype.kind in "fc":
            np.testing.assert_allclose(padded[col], unpadded[col],
                                       rtol=0, atol=1.1e-6)
        else:
            assert (padded[col] == unpadded[col]).all(), col


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

def test_http_front_end_roundtrip(tmp_path):
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.serve.http import HttpFrontEnd
    from shifu_tpu.serve.service import ScorerService

    models = _tiny_nn_dir(str(tmp_path / "models"))
    x = np.random.default_rng(5).normal(0, 1, (5, 12)).astype(np.float32)
    want = np.asarray(Scorer.from_dir(models).score(x)["mean"])

    with ScorerService(models_dir=models, max_delay=0.005,
                       aot_compile=False) as svc:
        front = HttpFrontEnd(svc, host="127.0.0.1", port=0).start()
        try:
            host, port = front.address
            base = f"http://{host}:{port}"

            body = json.dumps({"dense": x.tolist()}).encode()
            req = urllib.request.Request(
                base + "/score", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
            np.testing.assert_allclose(
                np.asarray(payload["scores"]["mean"], np.float64),
                want, rtol=1e-6, atol=1e-7)  # json float round-trip
            assert {"queue_s", "pad_s", "device_s",
                    "total_s"} <= set(payload["timing_ms"])
            assert payload["timing_ms"]["total_s"] > 0

            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as resp:
                assert resp.status == 200

            with urllib.request.urlopen(base + "/stats",
                                        timeout=10) as resp:
                stats = json.loads(resp.read())
            assert stats["batcher"]["requests"] >= 1

            bad = urllib.request.Request(
                base + "/score", data=b'{"dense": "nope"}',
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=10)
            assert ei.value.code == 400
        finally:
            front.close()
