"""Golden model-spec files: the npz container format
(`shifu_tpu/models/spec.py`) is the framework's cross-runtime model
binary — the analog of the reference's `.nn`/`.gbt` specs, which are
guarded by checked-in golden models scored in tests
(`core/dtrain/{NNModelEvalAndScore,TreeModelEvalAndScore,
IndependentTreeModel}Test.java`, SURVEY §4.5). These goldens pin:
(a) today's loader reads specs written by past rounds byte-for-byte,
(b) the portable (numpy-only) scorer reproduces the pinned scores.

Regenerate only on an INTENTIONAL format change (bump FORMAT_VERSION):
    python tests/test_spec_golden.py regen
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")

KINDS = ("nn", "gbt", "rf", "wdl", "bagging")


def _probe_inputs(kind, rng):
    dense = rng.normal(0, 1, (20, 6)).astype(np.float32)
    index = rng.integers(0, 4, (20, 2)).astype(np.int32)
    # tree probes must SPAN the cut table (0.5..6.5) so every bin —
    # and hence real routing through mid/high splits — is exercised
    raw_dense = rng.uniform(0.0, 7.0, (20, 6)).astype(np.float32)
    raw_codes = rng.integers(0, 5, (20, 2)).astype(np.int32)
    return dense, index, raw_dense, raw_codes


def _build_spec(kind, rng):
    """A small deterministic model of each kind, built directly from
    the model modules (no pipeline — goldens pin the container, not
    training)."""
    import jax

    if kind in ("nn", "bagging"):
        from shifu_tpu.models import nn as nn_mod
        spec = nn_mod.MLPSpec(input_dim=6, hidden_dims=(5,),
                              activations=("tanh",))
        meta = {"spec": spec.to_dict() if hasattr(spec, "to_dict")
                else spec.__dict__, "inputNames": [f"x{i}" for i in
                                                   range(6)]}
        params = jax.tree.map(np.asarray,
                              nn_mod.init_params(spec,
                                                 jax.random.PRNGKey(3)))
        if kind == "nn":
            return "nn", meta, params
        members = [{"kind": "nn", "meta": meta}, {"kind": "nn",
                                                  "meta": meta}]
        p2 = jax.tree.map(lambda a: a * 0.5, params)
        return "bagging", {"members": members, "assemble": "mean"}, \
            {"m0": params, "m1": p2}
    if kind in ("gbt", "rf"):
        import jax.numpy as jnp
        from shifu_tpu.models import gbdt
        cfg = gbdt.TreeConfig(max_depth=3, n_bins=8, learning_rate=0.3,
                              loss="log" if kind == "gbt" else "squared")
        bins = rng.integers(0, 7, (500, 6)).astype(np.int32)
        y = (bins[:, 0] + bins[:, 1] > 6).astype(np.float32)
        w = np.ones(500, np.float32)
        binsT = jnp.asarray(bins.T)
        fm = jnp.ones(6, jnp.float32)
        if kind == "gbt":
            trees, _ = gbdt.build_gbt(cfg, binsT, jnp.asarray(y),
                                      jnp.asarray(w), n_trees=3)
        else:
            gT = jnp.asarray(np.stack([y * w, y * w]))
            hT = jnp.asarray(np.stack([w, w]))
            trees = {k: np.asarray(v) for k, v in gbdt.build_forest(
                cfg, binsT, gT, hT, jnp.ones((2, 6), jnp.float32)).items()}
        # the tree-spec layout the trainers persist (train_tree.py:160):
        # params = {"trees": ..., "tables": {"num_cuts", "cat_map"}}
        num_cuts = np.linspace(0.5, 6.5, cfg.n_bins - 2)[:, None] \
            .repeat(6, 1).astype(np.float32)
        tables = gbdt.make_bin_tables(num_cuts, [], cfg.n_bins)
        meta = {"kind": kind,
                "treeConfig": {"max_depth": cfg.max_depth,
                               "n_bins": cfg.n_bins,
                               "learning_rate": cfg.learning_rate,
                               "loss": cfg.loss},
                "denseNames": [f"x{i}" for i in range(6)],
                "indexNames": []}
        return kind, meta, {"trees": {k: np.asarray(v)
                                      for k, v in trees.items()},
                            "tables": tables}
    if kind == "wdl":
        import jax
        from shifu_tpu.models import wdl
        spec = wdl.WDLSpec(dense_dim=6, n_cat=2, vocab_size=5,
                           embed_size=3, hidden_dims=(4,),
                           activations=("relu",))
        params = jax.tree.map(np.asarray,
                              wdl.init_params(spec,
                                              jax.random.PRNGKey(5)))
        meta = {"spec": spec.__dict__,
                "denseNames": [f"x{i}" for i in range(6)],
                "indexNames": ["c0", "c1"]}
        return "wdl", meta, params
    raise ValueError(kind)


def _score(kind, meta, params, rng):
    from shifu_tpu.portable import score_model
    dense, index, raw_dense, raw_codes = _probe_inputs(kind, rng)
    if kind in ("gbt", "rf"):
        # tree portable scorer bins the raw floats through the spec's
        # cut table itself
        return score_model(kind, meta, params, raw_dense=raw_dense,
                           raw_codes=None)
    if kind == "wdl":
        return score_model(kind, meta, params, dense=dense, index=index)
    return score_model(kind, meta, params, dense=dense)


@pytest.mark.parametrize("kind", KINDS)
def test_spec_golden_loads_and_scores(kind):
    from shifu_tpu.models.spec import load_model
    path = os.path.join(GOLDEN, f"{kind}.spec")
    assert os.path.exists(path), \
        "golden missing — run: python tests/test_spec_golden.py regen"
    k, meta, params = load_model(path)
    assert k == kind
    side = json.load(open(os.path.join(GOLDEN, f"{kind}.spec.json")))
    rng = np.random.default_rng(1234)
    got = _score(kind, meta, params, rng)
    np.testing.assert_allclose(got, np.asarray(side["scores"]),
                               rtol=1e-5, atol=1e-6)


def regen():
    from shifu_tpu.models.spec import save_model
    os.makedirs(GOLDEN, exist_ok=True)
    for kind in KINDS:
        rng = np.random.default_rng(42)
        k, meta, params = _build_spec(kind, rng)
        path = os.path.join(GOLDEN, f"{kind}.spec")
        save_model(path, k, meta, params)
        rng = np.random.default_rng(1234)
        scores = _score(k, meta, params, rng)
        with open(path + ".json", "w") as f:
            json.dump({"scores": np.asarray(scores).tolist()}, f)
        print(f"golden spec {kind}: {os.path.getsize(path)} bytes")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "regen":
        regen()
