"""Stats/binning kernel tests — exact-math unit tests in the style of
the reference's ColumnStatsCalculatorTest / EqualPopulationBinningTest
(SURVEY.md §4.1)."""

import numpy as np
import jax.numpy as jnp
import pytest

from shifu_tpu.ops import stats as stats_ops
from shifu_tpu.ops.binning import compute_numeric_binning
from shifu_tpu.config.model_config import BinningMethod


def test_column_metrics_matches_reference_formulas():
    # hand-computed from ColumnStatsCalculator.java semantics
    pos = np.array([13.0, 12.0, 95.0, 0.0])
    neg = np.array([170.0, 36.0, 29.0, 0.0])
    ks, iv, woe, bin_woe = stats_ops.column_metrics(pos, neg)
    sum_p, sum_n = pos.sum(), neg.sum()
    pr, nr = pos / sum_p, neg / sum_n
    exp_woe = np.log((pr + 1e-10) / (nr + 1e-10))
    np.testing.assert_allclose(bin_woe, exp_woe, rtol=1e-12)
    assert iv == pytest.approx(float(np.sum((pr - nr) * exp_woe)))
    assert ks == pytest.approx(
        100 * np.max(np.abs(np.cumsum(pr) - np.cumsum(nr))))
    assert woe == pytest.approx(np.log(sum_p / sum_n), rel=1e-6)


def test_column_metrics_single_class_returns_none():
    ks, iv, woe, _ = stats_ops.column_metrics(np.zeros(3), np.ones(3))
    assert ks is None and iv is None and woe is None


def test_weighted_quantiles_exact():
    v = np.arange(100, dtype=np.float32).reshape(-1, 1)
    w = np.ones_like(v)
    q = np.asarray(stats_ops.weighted_quantiles(jnp.asarray(v),
                                                jnp.asarray(w), 9))
    # deciles of 0..99
    np.testing.assert_allclose(q[:, 0], [9, 19, 29, 39, 49, 59, 69, 79, 89],
                               atol=1)


def test_weighted_quantiles_respects_weights():
    v = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
    w = np.array([[100.0], [1.0], [1.0], [1.0]], np.float32)
    q = np.asarray(stats_ops.weighted_quantiles(jnp.asarray(v),
                                                jnp.asarray(w), 1))
    assert q[0, 0] == 1.0  # median dominated by the heavy row


def test_bin_index_left_closed():
    cuts = jnp.asarray(np.array([[1.0], [2.0]], np.float32))  # bins (-inf,1),[1,2),[2,inf)
    v = jnp.asarray(np.array([[0.5], [1.0], [1.5], [2.0], [np.nan]], np.float32))
    idx = np.asarray(stats_ops.bin_index_numeric(v, cuts))
    np.testing.assert_array_equal(idx[:, 0], [0, 1, 1, 2, 3])  # NaN → missing slot


def test_bin_accumulate_counts():
    bin_idx = jnp.asarray(np.array([[0], [0], [1], [2], [2]], np.int32))
    tags = jnp.asarray(np.array([1, 0, 1, 0, 1], np.float32))
    w = jnp.asarray(np.array([1.0, 2.0, 1.0, 1.0, 3.0], np.float32))
    c = stats_ops.bin_accumulate(bin_idx, tags, w, 4)
    np.testing.assert_array_equal(np.asarray(c["count_pos"])[0], [1, 1, 1, 0])
    np.testing.assert_array_equal(np.asarray(c["count_neg"])[0], [1, 0, 1, 0])
    np.testing.assert_array_equal(np.asarray(c["weight_pos"])[0], [1, 1, 3, 0])
    np.testing.assert_array_equal(np.asarray(c["weight_neg"])[0], [2, 0, 1, 0])


def test_equal_positive_binning_balances_positives(rng):
    n = 5000
    y = (rng.random(n) < 0.3).astype(np.float32)
    x = rng.normal(0, 1, n).astype(np.float32) + y
    vals = x.reshape(-1, 1)
    b = compute_numeric_binning(vals, y, np.ones(n, np.float32),
                                BinningMethod.EqualPositive, 10)
    cuts = b.boundaries[0][1:]
    # positives per bin should be near-equal
    pos_vals = x[y == 1]
    counts, _ = np.histogram(pos_vals, bins=np.concatenate(
        ([-np.inf], cuts, [np.inf])))
    assert counts.std() / counts.mean() < 0.15


def test_equal_interval_binning():
    vals = np.linspace(0, 10, 101, dtype=np.float32).reshape(-1, 1)
    b = compute_numeric_binning(vals, np.zeros(101, np.float32),
                                np.ones(101, np.float32),
                                BinningMethod.EqualInterval, 5)
    np.testing.assert_allclose(b.boundaries[0][1:], [2, 4, 6, 8], atol=1e-5)


def test_moment_stats_nan_aware():
    v = jnp.asarray(np.array([[1.0], [2.0], [3.0], [np.nan]], np.float32))
    m = {k: np.asarray(x) for k, x in stats_ops.moment_stats(v).items()}
    assert m["mean"][0] == pytest.approx(2.0)
    assert m["missing"][0] == 1
    assert m["std"][0] == pytest.approx(1.0)
    assert m["min"][0] == 1.0 and m["max"][0] == 3.0


def test_psi():
    e = np.array([0.5, 0.5])
    a = np.array([0.6, 0.4])
    psi = stats_ops.psi_metric(e, a)
    assert psi == pytest.approx((0.5 - 0.6) * np.log(0.5 / 0.6)
                                + (0.5 - 0.4) * np.log(0.5 / 0.4), rel=1e-6)
