"""Streaming (>RAM) stats parity: the chunked two-pass sketch must
reproduce the resident stats within fine-histogram resolution, be
invariant to row order (all accumulations associative), and plug into
the same downstream pipeline (norm/train read only ColumnConfig)."""

import json
import os

import numpy as np
import pytest

from shifu_tpu.processor import init as init_proc, stats as stats_proc
from shifu_tpu.processor.base import ProcessorContext


def _stats_of(root):
    ccs = json.load(open(os.path.join(root, "ColumnConfig.json")))
    return {c["columnName"]: c for c in ccs}


def _run_init_stats(root, monkeypatch, chunk=None):
    if chunk is None:
        monkeypatch.delenv("SHIFU_TPU_STATS_CHUNK_ROWS", raising=False)
    else:
        monkeypatch.setenv("SHIFU_TPU_STATS_CHUNK_ROWS", str(chunk))
    ctx = ProcessorContext.load(root)
    assert init_proc.run(ctx) == 0
    ctx = ProcessorContext.load(root)
    assert stats_proc.run(ctx) == 0
    return _stats_of(root)


def test_streaming_stats_matches_resident(tmp_path, rng, monkeypatch):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=4000)
    resident = _run_init_stats(root, monkeypatch)
    streamed = _run_init_stats(root, monkeypatch, chunk=512)

    for name, res in resident.items():
        st_r, st_s = res["columnStats"], streamed[name]["columnStats"]
        bn_r, bn_s = res["columnBinning"], streamed[name]["columnBinning"]
        if not bn_r.get("binCountPos"):
            continue
        # exact-ish: moments + counts
        for k in ("totalCount", "missingCount"):
            assert st_r[k] == st_s[k], (name, k, st_r[k], st_s[k])
        for k in ("mean", "stdDev", "min", "max"):
            if st_r.get(k) is not None and st_s.get(k) is not None:
                assert abs(st_r[k] - st_s[k]) < 1e-3 * (1 + abs(st_r[k])), \
                    (name, k, st_r[k], st_s[k])
        # sketch-resolution: KS/IV close in relative terms (KS is on
        # the reference's 0-100-ish scale; boundary drift of 1/8192 of
        # the population shifts weak columns' KS by a few percent)
        for k in ("ks", "iv", "weightedKs", "weightedIv"):
            assert abs(st_r[k] - st_s[k]) < 0.2 + 0.1 * abs(st_r[k]), \
                (name, k, st_r[k], st_s[k])
        if bn_r.get("binCategory") is not None:
            # categorical: exact dict merge — vocab and counts equal
            assert bn_r["binCategory"] == bn_s["binCategory"], name
            assert bn_r["binCountPos"] == bn_s["binCountPos"], name
            assert bn_r["binCountNeg"] == bn_s["binCountNeg"], name
        else:
            b_r = np.asarray(bn_r["binBoundary"][1:], float)
            b_s = np.asarray(bn_s["binBoundary"][1:], float)
            vspan = max(st_r["max"] - st_r["min"], 1e-9)
            if len(b_r) == len(b_s):
                assert np.all(np.abs(b_r - b_s) < 0.01 * vspan + 1e-6), \
                    (name, b_r, b_s)
            # totals conserved across bins regardless of cut drift
            assert sum(bn_r["binCountPos"]) == sum(bn_s["binCountPos"]), name
            assert sum(bn_r["binCountNeg"]) == sum(bn_s["binCountNeg"]), name


def test_streaming_stats_order_invariant(tmp_path, rng, monkeypatch):
    """Label-sorted input produces identical streaming stats to the
    original order (associative accumulation — no order bias)."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=3000)
    a = _run_init_stats(root, monkeypatch, chunk=700)
    data_file = os.path.join(root, "data", "part-00000")
    with open(data_file) as f:
        lines = f.readlines()
    lines.sort(key=lambda ln: ln.rsplit("|", 1)[-1])
    with open(data_file, "w") as f:
        f.writelines(lines)
    b = _run_init_stats(root, monkeypatch, chunk=700)
    for name in a:
        sa, sb = a[name]["columnStats"], b[name]["columnStats"]
        for k in ("ks", "iv", "mean", "stdDev", "totalCount"):
            va, vb = sa.get(k), sb.get(k)
            if isinstance(va, float):
                assert abs(va - vb) < 1e-9 * (1 + abs(vb)), (name, k)
            else:
                assert va == vb, (name, k)


def test_streaming_stats_feeds_norm_and_train(tmp_path, rng, monkeypatch):
    """ColumnConfig from streaming stats drives norm → train → eval
    end-to-end (downstream reads only the configs)."""
    import json as _json

    from tests.synth import make_model_set
    from shifu_tpu.processor import (eval as eval_proc,
                                     norm as norm_proc,
                                     train as train_proc)
    root = make_model_set(tmp_path, rng, n_rows=3000)
    _run_init_stats(root, monkeypatch, chunk=512)
    monkeypatch.delenv("SHIFU_TPU_STATS_CHUNK_ROWS", raising=False)
    for proc in (norm_proc, train_proc, eval_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    ctx = ProcessorContext.load(root)
    perf = _json.load(open(ctx.path_finder.eval_performance_path("Eval1")))
    assert perf["areaUnderRoc"] > 0.85


def test_streaming_stats_sampling_and_filter(tmp_path, rng, monkeypatch):
    """sampleRate applies counter-based on the global row index:
    chunk size cannot change which rows are sampled."""
    import json as _json

    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=3000)
    mcp = os.path.join(root, "ModelConfig.json")
    mc = _json.load(open(mcp))
    mc["stats"]["sampleRate"] = 0.5
    _json.dump(mc, open(mcp, "w"))
    a = _run_init_stats(root, monkeypatch, chunk=300)
    b = _run_init_stats(root, monkeypatch, chunk=1100)
    for name in a:
        assert a[name]["columnStats"]["totalCount"] == \
            b[name]["columnStats"]["totalCount"], name


def test_streaming_stats_segment_rejected(tmp_path, rng, monkeypatch):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=500,
                          seg_expressions=["num_0 > 0"])
    monkeypatch.setenv("SHIFU_TPU_STATS_CHUNK_ROWS", "200")
    ctx = ProcessorContext.load(root)
    assert init_proc.run(ctx) == 0
    ctx = ProcessorContext.load(root)
    with pytest.raises(ValueError, match="resident stats"):
        stats_proc.run(ctx)


def test_pass_b_sparse_encoding_bitwise(rng):
    """The sharded Pass-B exchange ships sparse (indices, values) when
    a chunk's fine-histogram contribution is mostly zeros. Applying
    the encoding must equal the dense `fine += fc` BITWISE: the
    accumulator never holds -0.0, skipped zero addends are the
    identity, and each chunk's indices are unique, so the fancy-index
    scatter-add is the same operation sequence as the dense add."""
    from shifu_tpu.processor.stats_streaming import _apply_b, _encode_b

    shape = (4, 5, 64)
    fc = np.zeros(shape, np.float64)
    idx = rng.choice(fc.size, size=40, replace=False)
    fc.reshape(-1)[idx] = rng.normal(size=40)
    enc = _encode_b(fc)
    assert enc[0] == "sparse"
    base = np.abs(rng.normal(size=shape))   # counts-like accumulator
    dense, sparse = base.copy(), base.copy()
    dense += fc
    _apply_b(sparse, enc)
    assert dense.tobytes() == sparse.tobytes()

    # mostly-nonzero chunk: encoding falls back to the dense array
    fd = np.asarray(rng.normal(size=shape))
    enc2 = _encode_b(fd)
    assert enc2[0] == "dense"
    d2, s2 = base.copy(), base.copy()
    d2 += fd
    _apply_b(s2, enc2)
    assert d2.tobytes() == s2.tobytes()
