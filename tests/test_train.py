"""Training-slice tests: optimizers, trainer semantics, and the full
init→stats→norm→train→eval pipeline (the "one model end-to-end"
milestone; SURVEY.md §7 phase 3)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tpu.config.model_config import ModelConfig, ModelTrainConf
from shifu_tpu.models import nn as nn_mod
from shifu_tpu.processor import eval as eval_proc
from shifu_tpu.processor import init as init_proc
from shifu_tpu.processor import norm as norm_proc
from shifu_tpu.processor import stats as stats_proc
from shifu_tpu.processor import train as train_proc
from shifu_tpu.processor.base import ProcessorContext
from shifu_tpu.train.optimizers import make_optimizer
from shifu_tpu.train.trainer import bagging_weights, split_validation, train_nn


# ---------------------------------------------------------------------------
# optimizers
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prop", ["B", "Q", "R", "M", "N", "ADAM",
                                  "ADAGRAD", "RMSPROP"])
def test_optimizer_reduces_quadratic(prop):
    """Every Propagation mapping minimizes a quadratic (the reference's
    DTrainTest asserts error decreases per optimizer)."""
    opt = make_optimizer(prop, learning_rate=0.3)
    params = {"w": jnp.asarray([3.0, -2.0])}
    state = opt.init(params)
    loss = lambda p: jnp.sum(jnp.square(p["w"]))  # noqa: E731
    l0 = loss(params)
    for _ in range(60):
        g = jax.grad(loss)(params)
        upd, state = opt.update(g, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, upd)
    assert float(loss(params)) < float(l0) * 0.05


def test_unknown_propagation_raises():
    with pytest.raises(ValueError):
        make_optimizer("XYZ", 0.1)


# ---------------------------------------------------------------------------
# trainer pieces
# ---------------------------------------------------------------------------

def test_split_validation():
    tr, va = split_validation(1000, 0.2, seed=1)
    assert tr.sum() + va.sum() == 1000
    assert 100 < va.sum() < 300


def test_bagging_weights_poisson():
    w = bagging_weights(1000, 4, 1.0, with_replacement=True, seed=1)
    assert w.shape == (4, 1000)
    assert abs(w.mean() - 1.0) < 0.15
    assert (w >= 0).all() and (w == np.floor(w)).all()
    # bags differ
    assert not np.array_equal(w[0], w[1])


def test_bagging_weights_single_full_bag():
    w = bagging_weights(100, 1, 1.0, with_replacement=False, seed=1)
    assert (w == 1.0).all()


def test_bagging_weights_stratified_exact_class_counts():
    """train.stratifiedSample: every bag draws exactly
    round(rate · n_class) rows of each class
    (AbstractNNWorker.java:173,216-222 per-class bagging maps)."""
    labels = np.array([0] * 800 + [1] * 200, np.float32)
    w = bagging_weights(1000, 3, 0.5, with_replacement=False, seed=3,
                        labels=labels, stratified=True)
    for b in range(3):
        assert w[b, :800].sum() == 400     # negatives: 0.5 * 800
        assert w[b, 800:].sum() == 100     # positives: 0.5 * 200
        assert set(np.unique(w[b])) <= {0.0, 1.0}
    assert not np.array_equal(w[0], w[1])
    # with replacement: exact per-class totals as multiplicities
    w = bagging_weights(1000, 2, 0.5, with_replacement=True, seed=4,
                        labels=labels, stratified=True)
    assert w[0, :800].sum() == 400 and w[0, 800:].sum() == 100


def test_bagging_weights_stratified_nan_labels():
    """NaN labels (MTL primary-task gaps) must not crash stratified
    sampling — they sample at the plain rate."""
    labels = np.array([0] * 400 + [1] * 400 + [np.nan] * 200, np.float32)
    for repl in (False, True):
        w = bagging_weights(1000, 2, 0.5, with_replacement=repl, seed=9,
                            labels=labels, stratified=True)
        assert w[0, :400].sum() == 200 and w[0, 400:800].sum() == 200
        nan_frac = (w[:, 800:] > 0).mean()
        assert 0.3 < nan_frac < 0.7


def test_bagging_weights_neg_only_keeps_positives():
    """train.sampleNegOnly: positives always kept, negatives sampled
    at the bagging rate (wdl/WDLWorker.java:431-455)."""
    labels = np.array([0] * 900 + [1] * 100, np.float32)
    w = bagging_weights(1000, 2, 0.3, with_replacement=False, seed=5,
                        labels=labels, neg_only=True)
    assert (w[:, 900:] == 1.0).all()               # every positive, every bag
    frac_neg = w[:, :900].mean()
    assert 0.2 < frac_neg < 0.4                    # negatives ~rate


def test_bf16_compute_trains_and_scores(rng):
    """ComputeDtype=bfloat16 runs GEMMs/activations in bf16 with f32
    master weights: the model still learns, params and scores stay
    f32, and the saved spec round-trips through the scorer."""
    import jax.numpy as jnp
    from shifu_tpu.models import nn as nn_mod
    n = 2000
    x = rng.normal(0, 1, (n, 8)).astype(np.float32)
    beta = rng.normal(0, 1, 8).astype(np.float32)
    y = ((x @ beta) > 0).astype(np.float32)
    w = np.ones(n, np.float32)
    conf = ModelTrainConf.from_dict({
        "numTrainEpochs": 40, "baggingNum": 1, "validSetRate": 0.2,
        "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                   "ActivationFunc": ["tanh"], "LearningRate": 0.1,
                   "Propagation": "ADAM", "ComputeDtype": "bfloat16"}})
    res = train_nn(conf, x, y, w, seed=3)
    assert res.spec.compute_dtype == "bfloat16"
    p = res.params_per_bag[0]
    assert all(np.asarray(l["w"]).dtype == np.float32 for l in p)
    scores = nn_mod.forward(res.spec, p, jnp.asarray(x))
    assert scores.dtype == jnp.float32
    from shifu_tpu.ops.metrics import auc
    assert float(auc(scores, jnp.asarray(y))) > 0.9


def test_bagging_weights_neg_only_poisson_positives():
    """Under baggingWithReplacement, sampleNegOnly force-keeps
    positives (multiplicity ≥1) but leaves them in Poisson bagging —
    multiplicities >1 must occur (reference: only negatives are
    dropped; Poisson applies to kept rows)."""
    labels = np.array([0] * 500 + [1] * 500, np.float32)
    w = bagging_weights(1000, 2, 1.0, with_replacement=True, seed=11,
                        labels=labels, neg_only=True)
    pos = w[:, 500:]
    assert (pos >= 1.0).all()                      # force-keep
    assert (pos > 1.0).any()                       # Poisson, not pinned
    assert (w[:, :500] == 0.0).any()               # negatives can drop


def test_rf_stratified_sampling_threads_per_tree(rng):
    """RF honors stratifiedSample per tree (DTWorker.java:530,660):
    with a tiny positive class, stratified draws keep positives in
    every tree's bag at the class rate instead of Poisson noise."""
    from shifu_tpu.models.gbdt import TreeConfig, build_rf
    n = 800
    x = rng.normal(0, 1, (n, 4)).astype(np.float32)
    y = (rng.random(n) < 0.05).astype(np.float32)
    bins = np.clip((x * 8 + 32).astype(np.int32), 0, 63)
    cfg = TreeConfig(max_depth=3, n_bins=64, learning_rate=0.1,
                     loss="squared")
    w = np.ones(n, np.float32)
    for flags in ({"stratified": True}, {"neg_only": True}):
        trees = build_rf(cfg, bins, y, w, 4, "ALL", 0.5, seed=3, **flags)
        assert trees["feature"].shape[0] == 4
        assert np.isfinite(np.asarray(trees["leaf_value"])).all()


def test_chunk_bag_weights_neg_only_matches_semantics():
    """Streaming counter-based bag weights honor sampleNegOnly the
    same way the resident path does: positives multiplicity 1, only
    negatives sampled — and chunking cannot change membership."""
    from shifu_tpu.train.streaming import _chunk_bag_weights
    labels = (np.arange(1000) % 5 == 0).astype(np.float32)   # 20% pos
    whole = _chunk_bag_weights(2, 0.3, False, 7, 0, 1000,
                               labels=labels, neg_only=True)
    assert (whole[:, labels > 0.5] == 1.0).all()
    frac_neg = whole[:, labels < 0.5].mean()
    assert 0.2 < frac_neg < 0.4
    # same chunk bounds ⇒ identical membership every epoch/resume
    # (the counter-based scheme's invariant; chunk bounds are fixed
    # per run by chunk_rows)
    again = _chunk_bag_weights(2, 0.3, False, 7, 0, 1000,
                               labels=labels, neg_only=True)
    np.testing.assert_array_equal(whole, again)
    # neg-only mask composes on the SAME draws as plain sampling:
    # positions where the plain mask kept a negative stay kept
    plain = _chunk_bag_weights(2, 0.3, False, 7, 0, 1000)
    np.testing.assert_array_equal(whole[:, labels < 0.5],
                                  plain[:, labels < 0.5])


def test_train_nn_learns_xor_ish(rng):
    """Separable data: the trained net must beat chance massively."""
    n = 2000
    x = rng.normal(0, 1, (n, 4)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float32)
    w = np.ones(n, np.float32)
    conf = ModelTrainConf.from_dict({
        "numTrainEpochs": 60, "baggingNum": 1, "validSetRate": 0.2,
        "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [8],
                   "ActivationFunc": ["tanh"], "LearningRate": 0.2,
                   "Propagation": "ADAM"}})
    res = train_nn(conf, x, y, w, seed=3)
    assert float(res.best_val.min()) < 0.08
    assert res.train_errors.shape == (1, 60)


def test_train_nn_convergence_stop_freezes():
    """convergenceThreshold (ConvergeAndValidToleranceEarlyStop): once
    train error dips below the threshold, parameters freeze for the
    remaining scan epochs — val error exactly constant afterwards."""
    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (400, 3)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    conf = ModelTrainConf.from_dict({
        "numTrainEpochs": 60, "baggingNum": 1, "validSetRate": 0.25,
        "convergenceThreshold": 0.12,
        "params": {"NumHiddenLayers": 0, "NumHiddenNodes": [],
                   "ActivationFunc": [], "LearningRate": 0.5,
                   "Propagation": "B"}})
    res = train_nn(conf, x, y, np.ones(400, np.float32), seed=4)
    t = res.train_errors[0]
    assert t.min() <= 0.12  # threshold was reached
    v = res.val_errors[0]
    tail = v[-3:]
    assert np.allclose(tail, tail[0])


def test_train_nn_window_early_stop_on_overfit():
    """WindowEarlyStop: a big net on a tiny noisy set overfits, val
    error stops improving, the window triggers and updates freeze
    (exactly-constant val tail); the same run without earlyStoppingRounds
    keeps moving."""
    rng = np.random.default_rng(9)
    x = rng.normal(0, 1, (80, 6)).astype(np.float32)
    y = ((x[:, 0] + rng.normal(0, 1.0, 80)) > 0).astype(np.float32)
    base = {"numTrainEpochs": 150, "baggingNum": 1, "validSetRate": 0.4,
            "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [32],
                       "ActivationFunc": ["tanh"], "LearningRate": 0.5,
                       "Propagation": "ADAM"}}
    w = np.ones(80, np.float32)
    stop = train_nn(ModelTrainConf.from_dict(
        {**base, "earlyStoppingRounds": 5}), x, y, w, seed=4)
    free = train_nn(ModelTrainConf.from_dict(base), x, y, w, seed=4)
    v_stop, v_free = stop.val_errors[0], free.val_errors[0]

    def first_const(v):
        """First epoch from which the val error never changes again."""
        i = len(v) - 1
        while i > 0 and v[i - 1] == v[-1]:
            i -= 1
        return i

    assert np.all(v_stop[-50:] == v_stop[-1])     # frozen
    # the window froze the stopped run far earlier than the free run's
    # natural saturation (the free run may ALSO go exactly constant
    # once tanh saturates — order, not non-constancy, is the signal)
    assert first_const(v_stop) + 20 < first_const(v_free)


def test_bagging_vmap_trains_distinct_models(rng):
    x = rng.normal(0, 1, (600, 3)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    conf = ModelTrainConf.from_dict({
        "numTrainEpochs": 10, "baggingNum": 3, "baggingWithReplacement": True,
        "validSetRate": 0.2,
        "params": {"NumHiddenLayers": 1, "NumHiddenNodes": [4],
                   "ActivationFunc": ["tanh"], "LearningRate": 0.1,
                   "Propagation": "ADAM"}})
    res = train_nn(conf, x, y, np.ones(600, np.float32), seed=6)
    assert len(res.params_per_bag) == 3
    w0 = res.params_per_bag[0][0]["w"]
    w1 = res.params_per_bag[1][0]["w"]
    assert not np.allclose(w0, w1)


# ---------------------------------------------------------------------------
# end-to-end pipeline
# ---------------------------------------------------------------------------

def run_pipeline(root):
    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    ctx = ProcessorContext.load(root)
    assert eval_proc.run(ctx) == 0
    return ctx


def test_full_pipeline_nn(model_set):
    ctx = run_pipeline(model_set)
    perf_path = ctx.path_finder.eval_performance_path("Eval1")
    with open(perf_path) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85  # separable synthetic data
    assert os.path.exists(ctx.path_finder.model_path(0, "nn"))
    assert os.path.exists(ctx.path_finder.gain_chart_path("Eval1", "html"))
    assert os.path.exists(ctx.path_finder.eval_score_path("Eval1"))
    # gains distinct from pr/roc structures
    assert "actionRate" in perf["gains"][0]
    assert "precision" in perf["pr"][0]
    assert "fpr" in perf["roc"][0]


def test_full_pipeline_lr(tmp_path, rng):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1500, algorithm="LR",
                          train_params={"LearningRate": 0.5,
                                        "Propagation": "ADAM",
                                        "RegularizedConstant": 0.001})
    ctx = run_pipeline(root)
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85


@pytest.mark.parametrize("method", ["NATIVE", "ONEVSALL"])
def test_full_pipeline_multiclass(tmp_path, rng, method):
    """3-class pipeline: NATIVE = softmax head, ONEVSALL = one binary
    model per class (the reference's multiClassifyMethod decomposition,
    ModelTrainConf.java:74-90)."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=2400, n_classes=3,
                          multi_classify=method,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [12],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM"})
    ctx = run_pipeline(root)
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    # classes are linearly shifted in feature space → far above chance
    assert perf["accuracy"] > 0.55
    assert perf["classes"] == ["c0", "c1", "c2"]
    assert len(perf["perClass"]) == 3
    n_models = 3 if method == "ONEVSALL" else 1
    assert os.path.exists(ctx.path_finder.model_path(n_models - 1, "nn"))
    assert os.path.exists(ctx.path_finder.eval_confusion_path("Eval1"))
    with open(ctx.path_finder.eval_score_path("Eval1")) as f:
        header = f.readline().strip().split(",")
    assert header == ["tag", "weight", "class0", "class1", "class2",
                      "predicted"]


def test_multiclass_eval_streaming_parity(tmp_path, rng, monkeypatch):
    """>RAM multi-class eval streams the C×C confusion matrix chunk by
    chunk (counts merge exactly); forced tiny chunks must reproduce the
    resident outputs byte-for-byte — the reference's sort-based
    ConfusionMatrix (ConfusionMatrix.java:255-284) streams for any
    class count."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1200, n_classes=3,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [12],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM"})
    ctx = run_pipeline(root)

    def outputs():
        return (open(ctx.path_finder.eval_performance_path("Eval1")).read(),
                open(ctx.path_finder.eval_confusion_path("Eval1")).read(),
                open(ctx.path_finder.eval_score_path("Eval1")).read())

    res = outputs()
    from shifu_tpu.processor import eval as eval_proc
    monkeypatch.setenv("SHIFU_TPU_EVAL_CHUNK_ROWS", "111")
    assert eval_proc.run(ctx) == 0
    chk = outputs()
    assert chk[0] == res[0]      # performance json: exact counts
    assert chk[1] == res[1]      # confusion matrix
    # EvalScore.csv: same rows; scores numerically equal (scoring a
    # chunk vs the whole matrix can differ in the last printed ulp —
    # padding changes the GEMM tiling)
    import io
    import pandas as pd
    df_r = pd.read_csv(io.StringIO(res[2]))
    df_c = pd.read_csv(io.StringIO(chk[2]))
    assert list(df_r.columns) == list(df_c.columns)
    np.testing.assert_array_equal(df_c["tag"], df_r["tag"])
    np.testing.assert_array_equal(df_c["predicted"], df_r["predicted"])
    for col in df_r.columns:
        if col.startswith("class"):
            np.testing.assert_allclose(df_c[col], df_r[col], atol=2e-6)


def test_champion_challenger_eval(tmp_path, rng):
    """Benchmark score columns in the eval data get their own
    PerformanceResult next to the model's
    (EvalConfig#scoreMetaColumnNameFile, EvalModelProcessor:965-1004)."""
    import numpy as np
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1500)

    # append a noisy "champion" score column to the EVAL data only
    eval_dir = os.path.join(root, "evaldata")
    hdr_f = os.path.join(eval_dir, ".pig_header")
    hdr = open(hdr_f).read().strip().split("|")
    rows = [ln.rstrip("\n").split("|")
            for ln in open(os.path.join(eval_dir, "part-00000"))]
    tag_ix = hdr.index("diagnosis")
    champ = [("%.4f" % max(0.0, min(1.0, (0.8 if r[tag_ix] == "M" else 0.2)
                                    + rng.normal(0, 0.25)))) for r in rows]
    with open(hdr_f, "w") as f:
        f.write("|".join(hdr + ["champ_score"]) + "\n")
    with open(os.path.join(eval_dir, "part-00000"), "w") as f:
        for r, c in zip(rows, champ):
            f.write("|".join(r + [c]) + "\n")
    meta_file = os.path.join(root, "columns", "score.meta.names")
    with open(meta_file, "w") as f:
        f.write("champ_score\n")
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["evals"][0]["scoreMetaColumnNameFile"] = meta_file
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))

    ctx = run_pipeline(root)
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert "championAuc" in perf and "champ_score" in perf["championAuc"]
    # the champion is informative but noisy — beaten by the model
    assert 0.6 < perf["championAuc"]["champ_score"] < perf["areaUnderRoc"]
    champ_perf = os.path.join(ctx.path_finder.eval_base_path("Eval1"),
                              "EvalPerformance-champ_score.json")
    assert os.path.exists(champ_perf)


def test_grid_search_selects_best(tmp_path, rng):
    from tests.synth import make_model_set
    root = make_model_set(
        tmp_path, rng, n_rows=1000,
        train_params={"NumHiddenLayers": 1, "NumHiddenNodes": [[4], [8]],
                      "ActivationFunc": ["tanh"],
                      "LearningRate": [0.05, 0.2], "Propagation": "ADAM"})
    for proc in (init_proc, stats_proc, norm_proc):
        ctx = ProcessorContext.load(root)
        proc.run(ctx)
    ctx = ProcessorContext.load(root)
    assert train_proc.run(ctx) == 0
    assert os.path.exists(ctx.path_finder.model_path(0, "nn"))


def test_model_spec_roundtrip(tmp_path):
    from shifu_tpu.models.spec import load_model, save_model
    params = [{"w": np.ones((3, 2), np.float32), "b": np.zeros(2, np.float32)},
              {"w": np.ones((2, 1), np.float32), "b": np.zeros(1, np.float32)}]
    p = str(tmp_path / "model0.nn")
    save_model(p, "nn", {"spec": {"input_dim": 3}}, params)
    kind, meta, loaded = load_model(p)
    assert kind == "nn"
    assert meta["spec"]["input_dim"] == 3
    np.testing.assert_array_equal(loaded[0]["w"], params[0]["w"])
    np.testing.assert_array_equal(loaded[1]["b"], params[1]["b"])


def test_streaming_train_on_disk(tmp_path, rng):
    """train#trainOnDisk: norm lays out mmap-able .npy blocks and the
    trainer streams double-buffered chunks (>HBM path,
    MemoryDiskFloatMLDataSet analog)."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=3000,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [8],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM",
                                        "ChunkRows": 512})
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["train"]["trainOnDisk"] = True
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))

    ctx = run_pipeline(root)
    # streaming layout exists and training produced a model + eval
    norm_dir = ctx.path_finder.normalized_data_path()
    assert os.path.exists(os.path.join(norm_dir, "dense.npy"))
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85


def test_streaming_bagging(tmp_path, rng):
    """Streaming trains every bag at once (vmapped update over the bag
    axis, per-chunk Philox bag weights) — round 1 dropped bagging on
    the trainOnDisk path; round 2 must not."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=3000,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [8],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM",
                                        "ChunkRows": 700})
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["train"]["trainOnDisk"] = True
    mc["train"]["baggingNum"] = 2
    mc["train"]["baggingSampleRate"] = 0.8
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))

    ctx = run_pipeline(root)
    models = sorted(os.listdir(ctx.path_finder.models_path()))
    assert models == ["model0.nn", "model1.nn"]
    from shifu_tpu.models.spec import load_model
    _, _, p0 = load_model(ctx.path_finder.model_path(0, "nn"))
    _, _, p1 = load_model(ctx.path_finder.model_path(1, "nn"))
    # different bag samples ⇒ different weights
    assert np.abs(p0[0]["w"] - p1[0]["w"]).max() > 0
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85


def test_minibatch_mode(tmp_path, rng):
    """train#params MiniBatchRows: the main trainer runs an in-graph
    scan over shuffled mini-batches (bagging preserved) instead of one
    full-batch update per epoch."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=3000,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [8],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.05,
                                        "Propagation": "ADAM",
                                        "MiniBatchRows": 512})
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["train"]["baggingNum"] = 2
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))

    ctx = run_pipeline(root)
    models = sorted(os.listdir(ctx.path_finder.models_path()))
    assert models == ["model0.nn", "model1.nn"]
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85


def test_streaming_split_unbiased_on_label_sorted_input(tmp_path, rng):
    """Label-sorted input must not yield a single-class trailing
    validation split: `norm` writes the streaming layout in
    seeded-shuffled row order, so the trailing validSetRate block is
    ≈ a random split (the streaming analog of AbstractNNWorker.init:387
    random train/val assignment). VERDICT r2 Weak #4 / Next #6."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=3000,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [8],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM",
                                        "ChunkRows": 512})
    # adversarial row order: sort the raw data file by label so the
    # trailing fraction of the FILE is single-class
    data_file = os.path.join(root, "data", "part-00000")
    with open(data_file) as f:
        lines = f.readlines()
    lines.sort(key=lambda ln: ln.rsplit("|", 1)[-1])
    with open(data_file, "w") as f:
        f.writelines(lines)
    mc = json.load(open(os.path.join(root, "ModelConfig.json")))
    mc["train"]["trainOnDisk"] = True
    mc["train"]["validSetRate"] = 0.2
    mc["train"]["numTrainEpochs"] = 30
    mc["train"]["earlyStoppingRounds"] = 5
    json.dump(mc, open(os.path.join(root, "ModelConfig.json"), "w"))

    ctx = run_pipeline(root)
    # the streaming layout's trailing 20% holds BOTH classes at ≈ the
    # population rate (label-sorted writes would make it single-class)
    tags = np.load(os.path.join(ctx.path_finder.normalized_data_path(),
                                "tags.npy"))
    n_val = int(len(tags) * 0.2)
    val_pos_rate = float(tags[-n_val:].mean())
    pop_pos_rate = float(tags.mean())
    assert 0.5 * pop_pos_rate < val_pos_rate < 1.5 * pop_pos_rate, \
        f"validation split is biased: {val_pos_rate} vs {pop_pos_rate}"
    # and early-stop against that split still produces a real model
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85


# ---------------------------------------------------------------------------
# continuous training: structure growth with frozen layers
# (NNMaster.initOrRecoverParams:356-387, fitExistingModelIn:644-684,
#  NNStructureComparator, TrainModelProcessor:1389-1450)
# ---------------------------------------------------------------------------

def test_continuous_growth_absorbs_and_freezes(tmp_path, rng):
    """Train 1x8-hidden, resume as 1x16-hidden with layer 1 fixed:
    validation error starts at the old model's (exact functional
    absorption), and the absorbed input→hidden weights are
    bit-identical after training."""
    from tests.synth import make_model_set
    from shifu_tpu.models.spec import load_model
    root = make_model_set(tmp_path, rng, n_rows=2000,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [8],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM"})
    ctx = run_pipeline(root)
    old_kind, old_meta, old_params = load_model(ctx.path_finder.model_path(0, "nn"))
    assert old_meta["spec"]["hidden_dims"] == [8]
    with open(ctx.path_finder.val_error_path()) as f:
        old_val = json.load(f)["bestValError"][0]

    # grow to 16 hidden, freeze the absorbed input→hidden1 corner
    mcj = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcj))
    mc["train"]["isContinuous"] = True
    mc["train"]["params"]["NumHiddenNodes"] = [16]
    mc["train"]["params"]["FixedLayers"] = [1]
    json.dump(mc, open(mcj, "w"))
    ctx = ProcessorContext.load(root)
    assert train_proc.run(ctx) == 0

    new_kind, new_meta, new_params = load_model(
        ctx.path_finder.model_path(0, "nn"))
    assert new_meta["spec"]["hidden_dims"] == [16]
    # absorbed corner of the FIXED layer is bit-identical
    np.testing.assert_array_equal(np.asarray(new_params[0]["w"])[:, :8],
                                  np.asarray(old_params[0]["w"]))
    np.testing.assert_array_equal(np.asarray(new_params[0]["b"])[:8],
                                  np.asarray(old_params[0]["b"]))
    # the grown half of the fixed layer DID train (started as random
    # init from a fixed seed; all-zero would mean it was masked too)
    # and the output layer absorbed the old weights as its corner start
    with open(ctx.path_finder.val_error_path()) as f:
        new_val = json.load(f)["bestValError"][0]
    # exact absorption: the resumed run can only improve on the old
    # model's validation error (epoch 0 reproduces it exactly)
    assert new_val <= old_val * 1.05


def test_continuous_shrink_hard_errors(tmp_path, rng):
    """A new structure that cannot hold the old model must refuse, not
    warn-and-discard (GuaguaRuntimeException in initOrRecoverParams)."""
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1200,
                          train_params={"NumHiddenLayers": 1,
                                        "NumHiddenNodes": [8],
                                        "ActivationFunc": ["tanh"],
                                        "LearningRate": 0.1,
                                        "Propagation": "ADAM"})
    run_pipeline(root)
    mcj = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcj))
    mc["train"]["isContinuous"] = True
    mc["train"]["params"]["NumHiddenNodes"] = [4]
    json.dump(mc, open(mcj, "w"))
    ctx = ProcessorContext.load(root)
    with pytest.raises(ValueError, match="cannot hold"):
        train_proc.run(ctx)


def test_absorb_params_function_preserving(rng):
    """Same-depth growth starts as an exact functional copy: the grown
    units' cross-connections are zeroed so forward() matches the old
    network bit-for-bit at step 0."""
    old_spec = nn_mod.MLPSpec(input_dim=6, hidden_dims=(8,),
                              activations=("tanh",))
    new_spec = nn_mod.MLPSpec(input_dim=6, hidden_dims=(16,),
                              activations=("tanh",))
    k = jax.random.PRNGKey(3)
    old_p = nn_mod.init_params(old_spec, k)
    fresh = nn_mod.init_params(new_spec, jax.random.PRNGKey(4))
    grown, mask = nn_mod.absorb_params(old_p, fresh, fixed_layers=[1])
    x = jnp.asarray(rng.normal(0, 1, (32, 6)).astype(np.float32))
    # mathematically exact (grown cross-weights are zero); float
    # reassociation across the wider matmul leaves ~1 ulp of noise
    np.testing.assert_allclose(
        np.asarray(nn_mod.forward(old_spec, old_p, x)),
        np.asarray(nn_mod.forward(new_spec, grown, x)), atol=1e-6)
    # mask freezes exactly the absorbed indices of layer 1
    assert np.asarray(mask[0]["w"])[:, :8].sum() == 0
    assert np.asarray(mask[0]["w"])[:, 8:].min() == 1
    assert np.asarray(mask[1]["w"]).min() == 1   # output layer trains


def test_compare_structure():
    assert nn_mod.compare_structure([6, 8, 1], [6, 8, 1]) == 0
    assert nn_mod.compare_structure([6, 8, 1], [6, 16, 1]) == 1
    assert nn_mod.compare_structure([6, 8, 1], [10, 8, 1]) == 1
    assert nn_mod.compare_structure([6, 8, 1], [6, 8, 8, 1]) == 1
    assert nn_mod.compare_structure([6, 8, 1], [6, 4, 1]) == -1
    assert nn_mod.compare_structure([6, 8, 1], [4, 8, 1]) == -1
    assert nn_mod.compare_structure([6, 8, 1], [6, 8, 2]) == -1
    assert nn_mod.compare_structure([6, 8, 8, 1], [6, 8, 1]) == -1


def test_compare_structure_depth_growth_output_width():
    """Old output must fit the aligned new HIDDEN layer on depth
    growth, or absorption would crash on the corner copy."""
    assert nn_mod.compare_structure([6, 8, 4], [6, 8, 2, 4]) == -1
    assert nn_mod.compare_structure([6, 8, 4], [6, 8, 4, 4]) == 1
