"""Var-select tests: KS/IV filters, SE sensitivity ablation."""

import os

import numpy as np
import pytest

from shifu_tpu.config.column_config import load_column_configs
from shifu_tpu.processor import (init as init_proc, norm as norm_proc,
                                 stats as stats_proc,
                                 varselect as varsel_proc)
from shifu_tpu.processor.base import ProcessorContext


@pytest.fixture()
def statsed(model_set):
    ctx = ProcessorContext.load(model_set)
    init_proc.run(ctx)
    ctx = ProcessorContext.load(model_set)
    stats_proc.run(ctx)
    return model_set


def test_ks_filter_selects_informative(statsed):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.varSelect.filterBy = "KS"
    ctx.model_config.varSelect.filterNum = 4
    assert varsel_proc.run(ctx) == 0
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    sel = {c.columnName for c in ccs if c.finalSelect}
    assert len(sel) == 4
    # informative columns (even num_* get class shift; cat_* skewed)
    assert "num_0" in sel or "cat_0" in sel
    # pure-noise odd columns should rank last
    assert "num_1" not in sel


def test_iv_filter(statsed):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.varSelect.filterBy = "IV"
    ctx.model_config.varSelect.filterNum = 3
    assert varsel_proc.run(ctx) == 0
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    assert sum(c.finalSelect for c in ccs) == 3


def test_missing_rate_threshold(statsed):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.varSelect.missingRateThreshold = 0.001  # below 2% injected
    ctx.model_config.varSelect.filterBy = "KS"
    assert varsel_proc.run(ctx) == 0
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    assert sum(c.finalSelect for c in ccs) == 0  # all filtered by missing rate


def test_se_sensitivity(statsed):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.varSelect.filterBy = "SE"
    ctx.model_config.varSelect.filterNum = 4
    assert varsel_proc.run(ctx) == 0
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    sel = {c.columnName for c in ccs if c.finalSelect}
    assert len(sel) == 4
    # the se.0 ranking file exists with one line per source column
    se_path = ctx.path_finder.se_path(0)
    assert os.path.exists(se_path)
    lines = open(se_path).read().strip().splitlines()
    assert len(lines) == 8  # 6 numeric + 2 categorical
    # deltas sorted descending
    deltas = [float(l.split("\t")[1]) for l in lines]
    assert deltas == sorted(deltas, reverse=True)


def test_norm_after_varsel_uses_selection(statsed):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.varSelect.filterBy = "KS"
    ctx.model_config.varSelect.filterNum = 3
    varsel_proc.run(ctx)
    ctx = ProcessorContext.load(statsed)
    norm_proc.run(ctx)
    data, meta = norm_proc.load_normalized(
        ctx.path_finder.normalized_data_path())
    assert data["dense"].shape[1] == 3


def test_voted_genetic_wrapper(statsed):
    """filterBy=V: vmapped population of masked trainings, evolved, and
    voted (core/dvarsel wrapper). Informative columns (even num_*
    indices carry signal; odd are noise) should dominate the vote."""
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.varSelect.filterBy = "V"
    ctx.model_config.varSelect.wrapperNum = 4
    ctx.model_config.varSelect.params = {"population_live_size": 12,
                                         "population_multiply_cnt": 3,
                                         "expect_variable_cnt": 4}
    assert varsel_proc.run(ctx) == 0
    ccs = load_column_configs(os.path.join(statsed, "ColumnConfig.json"))
    sel = {c.columnName for c in ccs if c.finalSelect}
    assert len(sel) == 4
    # num_0/2/4 are the shifted (informative) columns; the wrapper must
    # find at least two of them
    assert len(sel & {"num_0", "num_2", "num_4", "cat_0", "cat_1"}) >= 3


def test_fi_filter_requires_tree(statsed):
    ctx = ProcessorContext.load(statsed)
    ctx.model_config.varSelect.filterBy = "FI"
    with pytest.raises(ValueError):
        varsel_proc.run(ctx)


def test_fi_filter_with_gbt(tmp_path, rng):
    from tests.synth import make_model_set
    root = make_model_set(tmp_path, rng, n_rows=1200, algorithm="GBT",
                          train_params={"TreeNum": 10, "MaxDepth": 3,
                                        "LearningRate": 0.3})
    for proc in (init_proc, stats_proc):
        ctx = ProcessorContext.load(root)
        proc.run(ctx)
    ctx = ProcessorContext.load(root)
    ctx.model_config.varSelect.filterBy = "FI"
    ctx.model_config.varSelect.filterNum = 4
    assert varsel_proc.run(ctx) == 0
    ccs = load_column_configs(os.path.join(root, "ColumnConfig.json"))
    sel = {c.columnName for c in ccs if c.finalSelect}
    assert len(sel) == 4
    assert len(sel & {"num_0", "num_2", "num_4", "cat_0", "cat_1"}) >= 3


def test_analysis_sampling_caps_big_sets(tmp_path, rng, monkeypatch):
    """When the raw set exceeds the analysis streaming threshold,
    varselect runs on a bounded uniform sample instead of reading the
    table resident (>RAM safety for the analysis steps)."""
    import json

    from tests.synth import make_model_set
    from shifu_tpu.processor import init as init_proc, stats as stats_proc
    from shifu_tpu.processor import varselect as vs_proc
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=2000)
    for proc in (init_proc, stats_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    # force the analysis trigger + a small cap
    monkeypatch.setenv("SHIFU_TPU_ANALYSIS_CHUNK_ROWS", "400")
    monkeypatch.setenv("SHIFU_TPU_ANALYSIS_MAX_ROWS", "900")
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["varSelect"]["filterBy"] = "SE"
    mc["varSelect"]["filterNum"] = 4
    json.dump(mc, open(mcp, "w"))
    ctx = ProcessorContext.load(root)
    assert vs_proc.run(ctx) == 0
    ccs = json.load(open(os.path.join(root, "ColumnConfig.json")))
    assert sum(1 for c in ccs if c.get("finalSelect")) == 4
    # the informative columns still win on the sample
    sel = {c["columnName"] for c in ccs if c.get("finalSelect")}
    assert "num_0" in sel or "num_2" in sel
