"""WDL and MTL model-family tests (reference analogs: wdl/mtl packages,
WideAndDeep layer graph, MultiTaskModel shared trunk)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shifu_tpu.models import mtl, wdl


def test_wdl_forward_shapes(rng):
    spec = wdl.WDLSpec(dense_dim=5, n_cat=3, vocab_size=7, embed_size=4,
                       hidden_dims=(8,), activations=("relu",))
    params = wdl.init_params(spec, jax.random.PRNGKey(0))
    d = jnp.asarray(rng.normal(0, 1, (10, 5)).astype(np.float32))
    i = jnp.asarray(rng.integers(0, 7, (10, 3)).astype(np.int32))
    p = wdl.forward(spec, params, d, i)
    assert p.shape == (10,)
    assert ((p > 0) & (p < 1)).all()


def test_wdl_learns_categorical_signal(rng):
    """Label depends only on a categorical column — embeddings + wide
    must capture it."""
    n = 3000
    idx = rng.integers(0, 6, (n, 2)).astype(np.int32)
    y = (idx[:, 0] >= 3).astype(np.float32)
    d = rng.normal(0, 1, (n, 3)).astype(np.float32)
    spec = wdl.WDLSpec(dense_dim=3, n_cat=2, vocab_size=7, embed_size=4,
                       hidden_dims=(8,), activations=("relu",))
    params = wdl.init_params(spec, jax.random.PRNGKey(1))
    import optax
    opt = optax.adam(0.05)
    state = opt.init(params)
    jd, ji, jy = jnp.asarray(d), jnp.asarray(idx), jnp.asarray(y)
    jw = jnp.ones(n)

    @jax.jit
    def step(params, state):
        loss, g = jax.value_and_grad(
            lambda p: wdl.loss_fn(spec, p, jd, ji, jy, jw))(params)
        upd, state = opt.update(g, state, params)
        return optax.apply_updates(params, upd), state, loss

    for _ in range(120):
        params, state, loss = step(params, state)
    p = np.asarray(wdl.forward(spec, params, jd, ji))
    acc = ((p > 0.5) == (y > 0.5)).mean()
    assert acc > 0.95


def test_mtl_forward_and_masked_loss(rng):
    spec = mtl.MTLSpec(input_dim=4, n_tasks=3, hidden_dims=(8,),
                       activations=("tanh",))
    params = mtl.init_params(spec, jax.random.PRNGKey(0))
    x = jnp.asarray(rng.normal(0, 1, (20, 4)).astype(np.float32))
    p = mtl.forward(spec, params, x)
    assert p.shape == (20, 3)
    y = np.full((20, 3), np.nan, np.float32)
    y[:, 0] = 1.0  # only task 0 labeled
    loss = mtl.loss_fn(spec, params, x, jnp.asarray(y), jnp.ones(20))
    assert np.isfinite(float(loss))


def test_full_pipeline_wdl(tmp_path, rng):
    from tests.synth import make_model_set
    from tests.test_train import run_pipeline
    root = make_model_set(
        tmp_path, rng, n_rows=2500, algorithm="WDL",
        norm_type="ZSCALE_INDEX",
        train_params={"NumHiddenLayers": 1, "NumHiddenNodes": [16],
                      "ActivationFunc": ["relu"], "LearningRate": 0.02,
                      "Propagation": "ADAM", "EmbedSize": 4})
    ctx = run_pipeline(root)
    with open(ctx.path_finder.eval_performance_path("Eval1")) as f:
        perf = json.load(f)
    assert perf["areaUnderRoc"] > 0.85
    assert os.path.exists(ctx.path_finder.model_path(0, "wdl"))


def test_full_pipeline_mtl(tmp_path, rng):
    """Two tasks: the synthetic 'diagnosis' plus a second derived tag
    column added to the raw files."""
    from tests.synth import make_model_set
    from shifu_tpu.processor.base import ProcessorContext
    from shifu_tpu.processor import (init as init_proc, stats as stats_proc,
                                     norm as norm_proc, train as train_proc)
    root = make_model_set(
        tmp_path, rng, n_rows=2000, algorithm="MTL",
        train_params={"NumHiddenLayers": 1, "NumHiddenNodes": [16],
                      "ActivationFunc": ["relu"], "LearningRate": 0.05,
                      "Propagation": "ADAM"})
    # add a second target column correlated with num_0
    import pandas as pd
    for sub in ("data", "evaldata"):
        dpath = os.path.join(root, sub, "part-00000")
        hpath = os.path.join(root, sub, ".pig_header")
        header = open(hpath).read().strip().split("|")
        df = pd.read_csv(dpath, sep="|", names=header, dtype=str)
        v = pd.to_numeric(df["num_0"], errors="coerce").fillna(0)
        df["second_tag"] = np.where(v > v.median(), "M", "B")
        df.to_csv(dpath, sep="|", header=False, index=False)
        with open(hpath, "w") as f:
            f.write("|".join(header + ["second_tag"]) + "\n")
    # point config at both targets
    mc_path = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mc_path))
    mc["dataSet"]["targetColumnName"] = "diagnosis|second_tag"
    json.dump(mc, open(mc_path, "w"), indent=2)

    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    assert os.path.exists(ctx.path_finder.model_path(0, "mtl"))

    # both task heads predictive on train data
    from shifu_tpu.models.spec import load_model
    kind, meta, params = load_model(ctx.path_finder.model_path(0, "mtl"))
    data, _ = norm_proc.load_normalized(ctx.path_finder.normalized_data_path())
    scores = mtl.predict_tasks(meta, params, data["dense"])
    assert scores.shape[1] == 2
    from shifu_tpu.ops.metrics import auc
    a0 = float(auc(jnp.asarray(scores[:, 0]), jnp.asarray(data["tags"])))
    assert a0 > 0.8


def test_wdl_streaming_train_on_disk(tmp_path, rng):
    """train#trainOnDisk routes WDL through the chunk-streamed core
    (mmap'd dense + embedding-index blocks; Criteo-scale analog)."""
    import json

    from tests.synth import make_model_set
    from shifu_tpu.processor import (eval as eval_proc, init as init_proc,
                                     norm as norm_proc,
                                     stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=2500, algorithm="WDL",
                          norm_type="ZSCALE_INDEX",
                          train_params={"NumHiddenNodes": [8],
                                        "ActivationFunc": ["relu"],
                                        "EmbedSize": 4,
                                        "LearningRate": 0.05,
                                        "Propagation": "ADAM",
                                        "ChunkRows": 500})
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    mc["train"]["trainOnDisk"] = True
    mc["train"]["numTrainEpochs"] = 30
    json.dump(mc, open(mcp, "w"))
    for proc in (init_proc, stats_proc, norm_proc, train_proc, eval_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    models = os.listdir(ctx.path_finder.models_path())
    assert models == ["model0.wdl"]
    perf = json.load(open(ctx.path_finder.eval_performance_path("Eval1")))
    assert perf["areaUnderRoc"] > 0.85, perf["areaUnderRoc"]


def test_mtl_streaming_train_on_disk(tmp_path, rng):
    """train#trainOnDisk routes MTL through the streaming core with the
    (R, T) task-tag block persisted in the mmap layout."""
    import json

    from tests.synth import make_model_set
    from shifu_tpu.processor import (init as init_proc, norm as norm_proc,
                                     stats as stats_proc,
                                     train as train_proc)
    from shifu_tpu.processor.base import ProcessorContext

    root = make_model_set(tmp_path, rng, n_rows=2500, algorithm="MTL",
                          train_params={"NumHiddenNodes": [8],
                                        "ActivationFunc": ["relu"],
                                        "LearningRate": 0.05,
                                        "Propagation": "ADAM",
                                        "ChunkRows": 500})
    mcp = os.path.join(root, "ModelConfig.json")
    mc = json.load(open(mcp))
    # two tasks over the same synthetic label (the second task is the
    # first's complement column; synth writes a single diagnosis column,
    # so duplicate it as task 2)
    mc["dataSet"]["targetColumnName"] = "diagnosis|diagnosis"
    mc["train"]["trainOnDisk"] = True
    mc["train"]["numTrainEpochs"] = 25
    json.dump(mc, open(mcp, "w"))
    for proc in (init_proc, stats_proc, norm_proc, train_proc):
        ctx = ProcessorContext.load(root)
        assert proc.run(ctx) == 0
    nd = ctx.path_finder.normalized_data_path()
    assert os.path.exists(os.path.join(nd, "task_tags.npy"))
    models = os.listdir(ctx.path_finder.models_path())
    assert models == ["model0.mtl"]
    from shifu_tpu.models.spec import load_model
    kind, meta2, params = load_model(ctx.path_finder.model_path(0, "mtl"))
    assert kind == "mtl" and meta2["spec"]["n_tasks"] == 2
