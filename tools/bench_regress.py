#!/usr/bin/env python
"""Bench-history regression gate over BENCH_LOCAL.jsonl.

For every (task, backend) series in the persisted bench log, compare
the NEWEST record against the trailing history (the median of the
earlier records' throughput): exit 1 when the newest throughput drops
more than ``--threshold`` percent below that median, or when the
newest record's roofline ``bound`` category flips (compute ↔ memory)
relative to the previous record of the same series — a bound flip
means the kernel moved to the other side of the ridge point, which is
a perf-structure change worth a human look even when raw throughput
held.

Throughput is whichever of THROUGHPUT_KEYS the record carries (tasks
measure different things: row-epochs/s for trainers, cells/s for the
histogram kernels, sustained QPS for serving, speedup ratios for the
DAG). Series with fewer than --min-history trailing records are
reported but never fail the gate — one data point is not a baseline.

Standing caveat (ROADMAP "Perf-claim caveat"): live `bench.py` TPU
capture has been failing in CI (axon probe timeouts) since r01, so
BENCH_LOCAL.jsonl records are refreshed manually on real hardware.
This gate therefore runs as an ADVISORY pass in tools/lint.sh — it
prints findings without failing lint — because a stale-but-consistent
history must not block unrelated PRs; run it directly (exit code
matters then) after refreshing the log on hardware.

    python tools/bench_regress.py [--log BENCH_LOCAL.jsonl]
                                  [--threshold 20] [--min-history 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# per-record throughput, first match wins (bigger = better for all)
THROUGHPUT_KEYS = (
    "row_epochs_per_sec", "row_trees_per_sec", "cells_per_sec",
    "rows_per_s", "qps_sustained", "stream_train_rows_per_s",
    "sens_col_rows_per_sec", "nn_row_epochs_per_sec", "dag_speedup",
    "speedup", "scores_per_sec",
)


def _throughput(rec: Dict) -> Optional[Tuple[str, float]]:
    for key in THROUGHPUT_KEYS:
        v = rec.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return key, float(v)
    return None


def _fleet_p99(rec: Dict, cls: str) -> Optional[float]:
    by_class = rec.get("p99_ms_by_class")
    if isinstance(by_class, dict):
        v = by_class.get(cls)
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def _bound(rec: Dict, key: str = "roofline") -> Optional[str]:
    roof = rec.get(key)
    if isinstance(roof, dict):
        b = roof.get("bound")
        return str(b) if b else None
    return None


def load_series(path: str) -> Dict[Tuple[str, str], List[Dict]]:
    series: Dict[Tuple[str, str], List[Dict]] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            backend = str(rec.get("backend", "?"))
            probe = rec.get("probe")
            if isinstance(probe, dict) and probe.get("fallback_reason"):
                # a record stamped with a probe fallback ran somewhere
                # it did not intend to (axon timeout → cpu): give it
                # its own series so it never dilutes — or trips — the
                # genuine hardware trend
                backend += "+fallback"
            key = (str(rec.get("task", "?")), backend)
            series.setdefault(key, []).append(rec)
    for recs in series.values():
        recs.sort(key=lambda r: r.get("ts", 0.0))
    return series


def check(path: str, threshold_pct: float, min_history: int) -> int:
    series = load_series(path)
    if not series:
        print(f"bench_regress: no records in {path}")
        return 0
    findings: List[str] = []
    for (task, backend), recs in sorted(series.items()):
        newest, history = recs[-1], recs[:-1]
        tp = _throughput(newest)
        label = f"{task}/{backend}"
        # continuous-refresh records (bench --task refresh) carry no
        # throughput key; their gates are absolute invariants, checked
        # BEFORE the throughput skip: the hot in-place swap must stay
        # cheaper than the evict+re-warm fallback it replaces, must
        # never recompile, and only guardrail-promoted challengers may
        # appear in a published record
        if task == "refresh":
            sw, rw = newest.get("swap_s"), newest.get("rewarm_s")
            if isinstance(sw, (int, float)) and \
                    isinstance(rw, (int, float)) and sw > rw:
                findings.append(
                    f"{label}: swap_s {sw:.4g} exceeds rewarm_s "
                    f"{rw:.4g} — the in-place swap lost to the "
                    "evict+re-warm fallback")
            scm = newest.get("swap_compile_misses")
            if isinstance(scm, (int, float)) and scm > 0:
                findings.append(
                    f"{label}: swap_compile_misses {scm:g} — the hot "
                    "swap recompiled resident executables")
            gr = newest.get("guardrail")
            if isinstance(gr, dict) and gr.get("decision") != "promote":
                findings.append(
                    f"{label}: guardrail decision "
                    f"{gr.get('decision')!r} in a published refresh "
                    "record — only promoted runs belong in the log")
        # live-promotion records (bench --task canary): two absolute
        # invariants first — a live cycle that failed even ONE client
        # request broke the headline promise (the primary never stops
        # serving; canary errors fall back, rollback just switches
        # routing off), and only guardrail-promoted live verdicts
        # belong in a published record. Rollback recovery latency
        # (breach verdict → incumbent re-pinned and serving) is
        # lower-is-better, ceilinged vs its trailing median below
        # like the ingest breach latency.
        if task == "canary":
            fr = newest.get("failed_requests")
            if isinstance(fr, (int, float)) and fr > 0:
                findings.append(
                    f"{label}: failed_requests {fr:g} — a live "
                    "promotion cycle dropped client requests")
            pv = newest.get("promote_verdict")
            if isinstance(pv, dict) and pv.get("decision") != "promote":
                findings.append(
                    f"{label}: promote_verdict "
                    f"{pv.get('decision')!r} in a published canary "
                    "record — only live-promoted runs belong in the "
                    "log")
            rr = newest.get("rollback_recovery_s")
            if isinstance(rr, (int, float)):
                hv = sorted(
                    float(r["rollback_recovery_s"]) for r in history
                    if isinstance(r.get("rollback_recovery_s"),
                                  (int, float)))
                if len(hv) >= min_history:
                    median = hv[len(hv) // 2]
                    ceil = median * (1.0 + threshold_pct / 100.0)
                    if rr > ceil:
                        findings.append(
                            f"{label}: rollback_recovery_s {rr:.4g} "
                            f"is {100.0 * (rr - median) / median:.1f}%"
                            f" above the trailing median {median:.4g}"
                            f" (threshold {threshold_pct:.0f}%)")
        # streaming-ingest records: append throughput rides the generic
        # rows_per_s gate and the replay verdict the generic
        # bitwise_identical gate below; breach-detection latency
        # (append → drift breach off a committed window) is
        # lower-is-better, ceilinged vs its trailing median like the
        # fleet p99s
        # tree-serving records (bench --task serving_tree): rows/s
        # rides the generic throughput gate and the per-size p99s the
        # generic p99_ms_by_class gate below; two absolute invariants
        # are checked here — the steady-state serve loop must never
        # recompile, and on the accelerator the fused Pallas ensemble
        # kernel must beat the interpretive bin+walk path it replaced
        # (CPU records are exempt: there the kernel runs in Pallas
        # interpret mode, which validates plumbing, not speed)
        if task == "serving_tree":
            ccm = newest.get("compile_cache_misses_steady")
            if isinstance(ccm, (int, float)) and ccm > 0:
                findings.append(
                    f"{label}: compile_cache_misses_steady {ccm:g} — "
                    "the tree-serving shape-bucket discipline leaked "
                    "a shape")
            fs = newest.get("fused_speedup")
            if backend == "tpu" and isinstance(fs, (int, float)) \
                    and fs < 1.0:
                findings.append(
                    f"{label}: fused_speedup {fs:.3f} < 1 — the fused "
                    "ensemble kernel lost to the xla bin+walk path "
                    "it replaced")
        if task == "ingest":
            bl = newest.get("breach_latency_s")
            if isinstance(bl, (int, float)):
                hv = sorted(
                    float(r["breach_latency_s"]) for r in history
                    if isinstance(r.get("breach_latency_s"),
                                  (int, float)))
                if len(hv) >= min_history:
                    median = hv[len(hv) // 2]
                    ceil = median * (1.0 + threshold_pct / 100.0)
                    if bl > ceil:
                        findings.append(
                            f"{label}: breach_latency_s {bl:.4g} is "
                            f"{100.0 * (bl - median) / median:.1f}% "
                            f"above the trailing median {median:.4g} "
                            f"(threshold {threshold_pct:.0f}%)")
        if tp is None:
            print(f"  {label}: no throughput key — skipped")
            continue
        key, value = tp
        hist_vals = [v for _, v in
                     filter(None, (_throughput(r) for r in history))]
        if len(hist_vals) < min_history:
            print(f"  {label}: {key}={value:.4g} — only "
                  f"{len(hist_vals)} trailing record(s), no baseline")
        else:
            hist_vals.sort()
            median = hist_vals[len(hist_vals) // 2]
            floor = median * (1.0 - threshold_pct / 100.0)
            delta = 100.0 * (value - median) / median
            if value < floor:
                findings.append(
                    f"{label}: {key} {value:.4g} is {-delta:.1f}% below "
                    f"the trailing median {median:.4g} "
                    f"(threshold {threshold_pct:.0f}%)")
            else:
                print(f"  {label}: {key}={value:.4g} "
                      f"({delta:+.1f}% vs median of {len(hist_vals)})")
        nb, pb = _bound(newest), next(
            (_bound(r) for r in reversed(history) if _bound(r)), None)
        if nb and pb and nb != pb:
            findings.append(
                f"{label}: roofline bound flipped {pb} → {nb} "
                "(crossed the ridge point — verify intentional)")
        # side-by-side records (gbt_stream) carry a second roofline for
        # the comparison mode — gate its bound the same way
        nhb = _bound(newest, "host_roofline")
        phb = next((_bound(r, "host_roofline") for r in reversed(history)
                    if _bound(r, "host_roofline")), None)
        if nhb and phb and nhb != phb:
            findings.append(
                f"{label}: host-tier roofline bound flipped "
                f"{phb} → {nhb} (comparison mode crossed the ridge)")
        # on the accelerator the device-resident state tier beating the
        # host tier IS the perf structure under test; losing it is a
        # regression even when headline throughput held. (CPU records
        # are exempt — both tiers live in host memory there.)
        sp = newest.get("resident_speedup")
        if backend == "tpu" and isinstance(sp, (int, float)) and sp < 1.0:
            findings.append(
                f"{label}: resident_speedup {sp:.2f} < 1 — the "
                "device-resident state tier lost to the host tier")
        # fleet records: per-priority-class p99 is lower-is-better
        # (the generic throughput gate above covers qps_sustained),
        # and the shed rate must not creep — both vs trailing medians,
        # advisory below --min-history like everything else
        if isinstance(newest.get("p99_ms_by_class"), dict):
            for cls in sorted(newest["p99_ms_by_class"]):
                nv = _fleet_p99(newest, cls)
                hv = sorted(v for v in (_fleet_p99(r, cls)
                                        for r in history)
                            if v is not None)
                if nv is None or len(hv) < min_history:
                    continue
                median = hv[len(hv) // 2]
                ceil = median * (1.0 + threshold_pct / 100.0)
                if nv > ceil:
                    findings.append(
                        f"{label}: p99_ms_by_class[{cls}] {nv:.4g} is "
                        f"{100.0 * (nv - median) / median:.1f}% above "
                        f"the trailing median {median:.4g} "
                        f"(threshold {threshold_pct:.0f}%)")
        sr = newest.get("shed_rate")
        if isinstance(sr, (int, float)):
            hv = sorted(float(r["shed_rate"]) for r in history
                        if isinstance(r.get("shed_rate"), (int, float)))
            if len(hv) >= min_history:
                median = hv[len(hv) // 2]
                # absolute headroom too: a 0 → 0.05 move shouldn't trip
                ceil = max(median * (1.0 + threshold_pct / 100.0),
                           median + 0.05)
                if sr > ceil:
                    findings.append(
                        f"{label}: shed_rate {sr:.4g} exceeds the "
                        f"trailing median {median:.4g} by more than "
                        f"{threshold_pct:.0f}% — low-priority traffic "
                        "is being shed harder than history")
        # pod-scale data plane (dist_stats): scaling efficiency has an
        # ABSOLUTE acceptance floor (0.7 at 2 hosts, ISSUE-14) on top
        # of the usual newest-vs-trailing-median gate, and the bitwise
        # parity verdict is a hard invariant, not a trend
        eff = newest.get("scaling_efficiency")
        if isinstance(eff, (int, float)):
            if eff < 0.7:
                findings.append(
                    f"{label}: scaling_efficiency {eff:.3f} below the "
                    "0.7 acceptance floor — the sharded data plane is "
                    "not splitting the work")
            hv = sorted(
                float(r["scaling_efficiency"]) for r in history
                if isinstance(r.get("scaling_efficiency"), (int, float)))
            if len(hv) >= min_history:
                median = hv[len(hv) // 2]
                floor = median * (1.0 - threshold_pct / 100.0)
                if eff < floor:
                    findings.append(
                        f"{label}: scaling_efficiency {eff:.3f} is "
                        f"{100.0 * (median - eff) / median:.1f}% below "
                        f"the trailing median {median:.3f} "
                        f"(threshold {threshold_pct:.0f}%)")
        sl = newest.get("slice")
        if isinstance(sl, dict):
            # multi-device pipeline records carry the sliced-vs-
            # timeshared A/B block: disjoint-slice concurrency must
            # never lose to the sequential schedule it replaces.
            # TPU records only — on one physical CPU the fake devices
            # share cores, so overlap is contention-bound and the
            # speedup hovers around 1 (CPU exempt, like fused_speedup)
            ss = sl.get("sliced_speedup")
            if backend == "tpu" and isinstance(ss, (int, float)) \
                    and ss < 1.0:
                findings.append(
                    f"{label}: sliced_speedup {ss:.2f} < 1 — device-"
                    "slice leasing lost to the timeshared sequential "
                    "schedule")
        if newest.get("bitwise_identical") is False:
            findings.append(
                f"{label}: bitwise_identical=false — sharded output "
                "diverged from the single-host run")
    if findings:
        print(f"bench_regress: {len(findings)} finding(s) in {path}:",
              file=sys.stderr)
        for f_ in findings:
            print(f"  REGRESSION {f_}", file=sys.stderr)
        return 1
    print(f"bench_regress: {len(series)} series clean in {path}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--log",
                    default=os.path.join(REPO, "BENCH_LOCAL.jsonl"))
    ap.add_argument("--threshold", type=float, default=20.0,
                    help="percent drop vs trailing median that fails")
    ap.add_argument("--min-history", type=int, default=2,
                    help="trailing records required to form a baseline")
    args = ap.parse_args(argv)
    if not os.path.exists(args.log):
        print(f"bench_regress: {args.log} absent — nothing to gate")
        return 0
    return check(args.log, args.threshold, args.min_history)


if __name__ == "__main__":
    sys.exit(main())
