#!/usr/bin/env bash
# One-shot TPU bench capture: probe the backend, run the full ladder
# (each sub-bench persists to BENCH_LOCAL.jsonl the moment it
# finishes), and commit whatever new records landed. Safe to re-run;
# exits nonzero without committing when the tunnel is down.
set -u
cd "$(dirname "$0")/.."

echo "[capture] probing backend..."
if ! timeout 90 python -c "import jax; print('backend:', jax.default_backend())"; then
    echo "[capture] backend unreachable — not running the ladder"
    exit 1
fi

before=$(wc -l < BENCH_LOCAL.jsonl 2>/dev/null || echo 0)
prof_before=$(wc -l < tools/profile_gbt.jsonl 2>/dev/null || echo 0)
echo "[capture] running bench ladder (records persist as they land)..."
python bench.py || true
after=$(wc -l < BENCH_LOCAL.jsonl 2>/dev/null || echo 0)

echo "[capture] GBT component attribution (tools/profile_gbt.py)..."
timeout 2400 python tools/profile_gbt.py 11000000 5 || true
prof_after=$(wc -l < tools/profile_gbt.jsonl 2>/dev/null || echo 0)

new_files=""
if [ "$prof_after" -gt "$prof_before" ]; then
    new_files="tools/profile_gbt.jsonl"
fi
if [ "$after" -gt "$before" ] || [ -n "$new_files" ]; then
    echo "[capture] committing new measurement data"
    # propagate git's exit code: a failed commit (hook, lock, identity)
    # must not report capture success — the records would sit
    # uncommitted while callers believe they landed
    git commit -m "Capture TPU bench records ($((after - before)) new in BENCH_LOCAL.jsonl)

No-Verification-Needed: measurement-data-only commit" -- BENCH_LOCAL.jsonl $new_files
    exit $?
fi
echo "[capture] nothing new persisted"
exit 1
