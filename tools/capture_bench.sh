#!/usr/bin/env bash
# One-shot TPU bench capture: probe the backend, run the full ladder
# (each sub-bench persists to BENCH_LOCAL.jsonl the moment it
# finishes), and commit whatever new records landed. Safe to re-run;
# exits nonzero without committing when the tunnel is down.
set -u
cd "$(dirname "$0")/.."

echo "[capture] probing backend..."
if ! timeout 90 python -c "import jax; print('backend:', jax.default_backend())"; then
    echo "[capture] backend unreachable — not running the ladder"
    exit 1
fi

before=$(wc -l < BENCH_LOCAL.jsonl 2>/dev/null || echo 0)
echo "[capture] running bench ladder (records persist as they land)..."
python bench.py || true
after=$(wc -l < BENCH_LOCAL.jsonl 2>/dev/null || echo 0)

if [ "$after" -gt "$before" ]; then
    echo "[capture] $((after - before)) new record(s) — committing"
    git commit -m "Capture TPU bench records ($((after - before)) new in BENCH_LOCAL.jsonl)

No-Verification-Needed: measurement-data-only commit (BENCH_LOCAL.jsonl)" -- BENCH_LOCAL.jsonl
else
    echo "[capture] no new records persisted"
    exit 1
fi
