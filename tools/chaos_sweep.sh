#!/usr/bin/env bash
# Full chaos sweep: for EVERY registered fault site (resilience.
# FAULT_SITES), build a fresh tiny model set, inject one fault at that
# site (SHIFU_TPU_FAULT=<site>:<kind>:1) and drive the real pipeline
# (init -> stats -> norm -> train -> eval) under a hard timeout.
#
# The hang-proofing contract checked per site:
#   - the pipeline either SUCCEEDS (retry layer absorbed the fault), or
#   - fails PROMPTLY with output that NAMES the injected site, and
#   - NEVER trips the per-site wall-clock timeout (a hang is the one
#     unforgivable outcome).
#
# tests/test_chaos.py is the fast in-tree subset of this matrix wired
# into tier-1; run this script for the exhaustive sweep.
#
# Usage: tools/chaos_sweep.sh [kind]        (kind: oserror|timeout, default oserror)

set -u -o pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
KIND="${1:-oserror}"
PER_SITE_TIMEOUT="${CHAOS_TIMEOUT_S:-300}"
WORK="$(mktemp -d /tmp/chaos_sweep.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT

export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS=cpu
export SHIFU_TPU_RETRY_BASE_S=0.01
# the ckpt.* sites only fire when training actually checkpoints, and
# the async-writer seams (ckpt.stage/ckpt.publish) only exist with the
# background writer on
export SHIFU_TPU_CKPT_ASYNC=1

SITES=$(python -c \
  "from shifu_tpu.resilience import FAULT_SITES; print('\n'.join(FAULT_SITES))")

build_model_set() {  # $1 = dest dir
  python - "$1" <<'PYEOF'
import sys
import numpy as np
from tests.synth import make_model_set
# CheckpointInterval=2 makes train pass ckpt.save/stage/publish/saved
# every other epoch, so those sites are exercised, not skipped
print(make_model_set(sys.argv[1], np.random.default_rng(7), n_rows=300,
                     train_params={"CheckpointInterval": 2}))
PYEOF
}

run_refresh_drill() {  # $1 = model-set dir, $2 = site; the standard
  # init->eval pipeline never reaches the refresh.* sites, so they get
  # the closed-loop drill: train+publish an incumbent, warm a fleet,
  # inject the fault into a breach-triggered refresh, and hold the
  # invariant — the incumbent keeps serving and HEAD is either unmoved
  # or cleanly rolled back, with no .tmp residue.
  python - "$1" "$2" <<'PYEOF'
import os, sys
import numpy as np
ms, site = sys.argv[1], sys.argv[2]
from shifu_tpu.cli import main as cli_main
for cmd in ("init", "stats", "norm", "train"):
    assert cli_main(["--dir", ms, cmd]) == 0, cmd
from shifu_tpu import registry, resilience
from shifu_tpu.processor.base import ProcessorContext
from shifu_tpu.serve.fleet import FleetService
from shifu_tpu.obs.health.refresh import RefreshController
import pandas as pd
reg = os.path.join(os.path.dirname(ms), "reg")
v1 = registry.publish(reg, "m", os.path.join(ms, "models"), ladder=(1, 4))
hdr = open(os.path.join(ms, "data", ".pig_header")).read().strip().split("|")
df = pd.read_csv(os.path.join(ms, "data", "part-00000"), sep="|",
                 names=hdr, dtype=str)
with FleetService(reg, workspace_root=ms, hbm_budget_mb=0) as fleet:
    _, _, man = registry.resolve(reg, "m")
    x = np.random.default_rng(3).normal(
        0, 1, (2, man["input_dim"])).astype(np.float32)
    ctl = RefreshController(ProcessorContext.load(ms), registry_root=reg,
                            model_name="m", fleet=fleet, tolerance=0.5)
    ctl.note_window(df)
    resilience.reset_faults()
    outcome = ctl.handle_breach({"slo": "drift", "state": "breach"})
    # invariant: whatever the fault did, the fleet still answers and
    # HEAD names a complete version
    fleet.submit("m", dense=x)
    head = registry.head(reg, "m")
    assert head is not None
    registry.resolve(reg, "m")   # raises if HEAD dangles
    if outcome not in ("promoted",):
        assert head == v1, (outcome, head)
stranded = [os.path.join(d, f) for d, _, fs in os.walk(ms)
            for f in fs if f.startswith(".tmp.")]
assert not stranded, stranded
print(f"refresh drill at {site}: outcome={outcome}, HEAD={head}, "
      "incumbent kept serving")
PYEOF
}

run_canary_drill() {  # $1 = model-set dir, $2 = site; the standard
  # pipeline never reaches the live-promotion sites, so canary.* and
  # shadow.* get the closed-loop drill: publish an incumbent, warm a
  # fleet, drive a staged live promotion under live traffic with the
  # fault armed, and hold the invariant — the primary answers before,
  # during and after, recover() leaves HEAD on the incumbent, and no
  # canary state file or .tmp residue survives.
  python - "$1" "$2" <<'PYEOF'
import os, sys, threading, time, traceback
import numpy as np
ms, site = sys.argv[1], sys.argv[2]
from shifu_tpu.cli import main as cli_main
for cmd in ("init", "stats", "norm", "train"):
    assert cli_main(["--dir", ms, cmd]) == 0, cmd
from shifu_tpu import registry, resilience
from shifu_tpu.obs.health.canary import CanaryController, read_state
from shifu_tpu.serve.fleet import FleetService
reg = os.path.join(os.path.dirname(ms), "reg")
v1 = registry.publish(reg, "m", os.path.join(ms, "models"), ladder=(1, 4))
with FleetService(reg, workspace_root=ms, hbm_budget_mb=0) as fleet:
    _, _, man = registry.resolve(reg, "m")
    x = np.random.default_rng(3).normal(
        0, 1, (4, man["input_dim"])).astype(np.float32)
    fleet.submit("m", dense=x)
    stop = threading.Event()
    def client():  # the live traffic the arms mirror and sample
        while not stop.is_set():
            try:
                fleet.submit_timed("m", dense=x, timeout=30.0)
            except Exception:
                pass
            time.sleep(0.01)
    threading.Thread(target=client, daemon=True).start()
    # tiny quorum so every stage transition is reached in seconds;
    # psi_max=-1 forces the decide verdict onto the rollback branch
    # (any PSI exceeds it), so one pass walks start -> shadow ->
    # canary -> decide -> rollback and every canary.* site fires
    ctl = CanaryController(fleet, reg, "m", store_root=ms,
                           shadow_pct=1.0, canary_pct=0.5,
                           min_requests=4, window_s=60.0,
                           psi_max=-1.0, poll_s=0.01)
    resilience.reset_faults()
    err = None
    try:
        outcome = ctl.run(os.path.join(ms, "models"), "drill")["outcome"]
    except Exception as e:
        err, outcome = e, "raised"
        traceback.print_exc()
        CanaryController.recover(reg, "m", fleet=fleet, store_root=ms)
    stop.set()
    # invariant: whatever the fault did, the primary still answers,
    # HEAD names a complete version, the arm is down, and no canary
    # state file survives recovery
    fleet.submit("m", dense=x)
    head = registry.head(reg, "m")
    registry.resolve(reg, "m")   # raises if HEAD dangles
    assert read_state(reg, "m") is None
    assert fleet.arm_stats("m") is None
    if outcome != "promoted":
        assert head == v1, (outcome, head)
stranded = [os.path.join(d, f) for d, _, fs in os.walk(reg)
            for f in fs if f.startswith(".tmp.")]
assert not stranded, stranded
print(f"canary drill at {site}: outcome={outcome}, HEAD={head}, "
      "primary kept serving")
if err is not None:
    raise err
PYEOF
}

run_ingest_drill() {  # $1 = work dir, $2 = site; the pipeline never
  # touches the row log, so the ingest.* sites get the closed-loop
  # drill: append + seal + exactly-once window read under the fault,
  # SIGKILL the writer mid-seal, rerun, and hold the invariant — the
  # committed window re-reads bitwise and no .tmp residue survives.
  python - "$1" "$2" <<'PYEOF'
import hashlib, os, signal, subprocess, sys
work, site = sys.argv[1], sys.argv[2]
from shifu_tpu import resilience
from shifu_tpu.data.ingest import RowLog
root = os.path.join(work, "rowlog")
script = (
    "from shifu_tpu.data.ingest import RowLog\n"
    f"lg = RowLog({root!r}, header=['a', 'b'], segment_rows=4)\n"
    "lg.append([f'{i}|x{i}' for i in range(10)])\n"
    "lg.seal_all()\n"
    "w = lg.read_window('watch')\n"
    "lg.commit('watch', w.end)\n"
    "print('ROWS', len(w.lines))\n")
# 1. the injected fault: ingest faults surface to the caller (the
#    feed's retry loop owns the redelivery), so the first run must
#    fail PROMPTLY with output naming the site (the SIGKILL variant
#    is tests/test_chaos.py's job)
resilience.reset_faults()
env = dict(os.environ)
p = subprocess.run([sys.executable, "-c", script], env=env,
                   capture_output=True, text=True)
if p.returncode != 0:
    fault = env.get("SHIFU_TPU_FAULT", "")
    if f"injected {fault.split(':')[1]} at {site}" not in \
            p.stdout + p.stderr:
        sys.stderr.write(p.stdout + p.stderr)
        sys.exit(p.returncode)   # died without naming the site
    sys.stderr.write(f"first run failed naming {site}; rerunning\n")
# 2. rerun clean: the log recovers from whatever the fault tore, and
#    the committed window re-reads bitwise forever
env.pop("SHIFU_TPU_FAULT", None)
p = subprocess.run([sys.executable, "-c", script], env=env,
                   capture_output=True, text=True)
if p.returncode != 0:
    sys.stderr.write(p.stdout + p.stderr)
    sys.exit(p.returncode)
lg = RowLog(root)
start = {"0": {"seq": 1, "row": 0}}
lines = lg.read_range(start, lg.committed_offset("watch"))
d1 = hashlib.sha256("\n".join(lines).encode()).hexdigest()
d2 = hashlib.sha256("\n".join(
    RowLog(root).read_range(start, lg.committed_offset("watch"))
    ).encode()).hexdigest()
assert d1 == d2, "committed window replay diverged"
# one or two whole batches, depending on where the fault landed —
# never a torn, duplicated, or interleaved row
batch = [f"{i}|x{i}" for i in range(10)]
assert len(lines) in (10, 20) and all(
    lines[k:k + 10] == batch for k in range(0, len(lines), 10)), lines
stranded = [os.path.join(d, f) for d, _, fs in os.walk(root)
            for f in fs if f.startswith(".tmp.")]
assert not stranded, stranded
print(f"ingest drill at {site}: {len(lines)} rows committed, replay "
      "bitwise, no residue")
PYEOF
}

run_dag_slice_drill() {  # $1 = work dir, $2 = site; the single-command
  # pipeline never builds a sliced device DAG, so dag.slice gets the
  # closed-loop drill: a synthetic device DAG over a declared 8-device
  # pool with the fault armed at the lease-acquire seam — the faulted
  # node must fail naming the site, its slice must return to the pool
  # WITHIN the run (the independent whole-pool sibling can only be
  # admitted on the freed devices), and a clean rerun must re-lease
  # everything with no leaked slice.
  python - "$1" "$2" <<'PYEOF'
import os, sys
work, site = sys.argv[1], sys.argv[2]
kind = os.environ["SHIFU_TPU_FAULT"].split(":")[1]
from shifu_tpu import resilience
from shifu_tpu.pipeline.scheduler import DagError, Node, run_dag
os.environ["SHIFU_TPU_DAG_SLICE"] = "1"
os.environ["SHIFU_TPU_DAG_DEVICES"] = "8"

def build(ran):
    return [
        Node("a", lambda lease_env=None: ran.append("a"), devices=8),
        Node("b", lambda lease_env=None: ran.append("b"),
             deps=("a",), devices=4),
        Node("c", lambda lease_env=None: ran.append("c"), devices=8),
    ]

resilience.reset_faults()
ran = []
try:
    run_dag(build(ran), workers=2, root=work, label="drill")
    raise SystemExit(f"fault at {site} never surfaced")
except DagError as e:
    assert f"injected {kind} at {site}" in str(e.__cause__), e
    states = {r["node"]: r["state"] for r in e.report["nodes"]}
    assert states == {"a": "failed", "b": "poisoned", "c": "done"}, states
    assert ran == ["c"], ran   # c's demand-8 lease proves the return
    by = {r["node"]: r for r in e.report["nodes"]}
    assert by["a"]["devices"] == 8 and by["c"]["devices"] == 8
resilience.clear_abort()
# clean rerun: re-leases with no leaked slice
os.environ.pop("SHIFU_TPU_FAULT", None)
resilience.reset_faults()
ran = []
rep = run_dag(build(ran), workers=2, root=work, label="drill")
assert all(r["state"] == "done" for r in rep["nodes"])
assert sorted(ran) == ["a", "b", "c"]
print(f"dag.slice drill: lease returned within-run, clean rerun "
      f"re-leased {rep['total_devices']} devices, no leak")
PYEOF
}

pass=0 fail=0 hang=0
declare -a HUNG BROKE

for site in $SITES; do
  dest="$WORK/$site"
  mkdir -p "$dest"
  ms="$(build_model_set "$dest")" || { echo "FATAL: model-set build failed"; exit 2; }

  log="$WORK/$site.log"
  rc=0
  case "$site" in
    refresh.*)
      SHIFU_TPU_FAULT="$site:$KIND:1" \
        timeout -k 10 "$PER_SITE_TIMEOUT" \
        bash -c "$(declare -f run_refresh_drill); run_refresh_drill '$ms' '$site'" \
        >>"$log" 2>&1
      rc=$?
      ;;
    ingest.*)
      SHIFU_TPU_FAULT="$site:$KIND:1" \
        timeout -k 10 "$PER_SITE_TIMEOUT" \
        bash -c "$(declare -f run_ingest_drill); run_ingest_drill '$dest' '$site'" \
        >>"$log" 2>&1
      rc=$?
      ;;
    dag.slice)
      SHIFU_TPU_FAULT="$site:$KIND:1" \
        timeout -k 10 "$PER_SITE_TIMEOUT" \
        bash -c "$(declare -f run_dag_slice_drill); run_dag_slice_drill '$dest' '$site'" \
        >>"$log" 2>&1
      rc=$?
      ;;
    canary.*|shadow.*)
      SHIFU_TPU_FAULT="$site:$KIND:1" \
        timeout -k 10 "$PER_SITE_TIMEOUT" \
        bash -c "$(declare -f run_canary_drill); run_canary_drill '$ms' '$site'" \
        >>"$log" 2>&1
      rc=$?
      ;;
    *)
      for cmd in init stats norm train eval; do
        SHIFU_TPU_FAULT="$site:$KIND:1" \
          timeout -k 10 "$PER_SITE_TIMEOUT" \
          python -m shifu_tpu.cli --dir "$ms" "$cmd" >>"$log" 2>&1
        rc=$?
        [ "$rc" -ne 0 ] && break
      done
      ;;
  esac

  if [ "$rc" -eq 0 ]; then
    echo "PASS  $site (fault absorbed, pipeline succeeded)"
    pass=$((pass+1))
  elif [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "HANG  $site (timed out after ${PER_SITE_TIMEOUT}s)"
    hang=$((hang+1)); HUNG+=("$site")
  elif grep -q "injected $KIND at $site" "$log"; then
    echo "PASS  $site (failed fast, error names the site, rc=$rc)"
    pass=$((pass+1))
  else
    echo "FAIL  $site (rc=$rc but error does not name the site; see $log)"
    fail=$((fail+1)); BROKE+=("$site")
  fi
done

echo
echo "chaos sweep ($KIND): $pass pass, $fail contract-fail, $hang hang"
[ "$hang" -gt 0 ] && echo "  hung sites: ${HUNG[*]}"
[ "$fail" -gt 0 ] && echo "  broken sites: ${BROKE[*]}"
echo "site list is lint-enforced: tools/lint.sh (unregistered-fault-site)"
echo "  keeps FAULT_SITES and the fault_point calls in sync both ways;"
echo "  re-run with SHIFU_TPU_LOCKCHECK=1 to also certify lock ordering"
[ $((fail + hang)) -eq 0 ]
