#!/usr/bin/env python
"""Schema-drift gate: README-documented steps.jsonl stage fields must
exist among the keys the code actually emits.

README describes the per-stage timing keys carried in each
``tmp/metrics/steps.jsonl`` record's ``inputPipeline`` block
(`host_parse_s`, `ckpt_stall_s`, `compile_s`, ...). The only writers
of that block are ``pipeline.add_stage_time`` / ``add_stage_count``,
so the emitted vocabulary is statically enumerable: this script
AST-walks ``shifu_tpu/`` collecting every string-literal stage name
passed to those calls (plus the string defaults of ``stage=``
parameters, which name the key when callers rely on the default),
extracts the backticked stage tokens README claims, and exits 1 when
documented ⊄ emitted — a renamed or deleted stage key must not leave
the README describing fields that no longer appear in the logs.

Token heuristic: backticked lowercase identifiers ending in ``_s``,
``_hits`` or ``_misses`` are treated as stage fields; ``*per_s`` /
``*_frac`` tokens are bench.py record keys, not steps.jsonl stages,
and are skipped.

The ``roofline`` block (train-step records + every bench.py task
record) is pinned the same way: its schema is the single
``profiling.ROOFLINE_FIELDS`` tuple (AST-read, no imports), every
field must be documented in README's Raw speed section, and any
backticked README token that LOOKS like a roofline field (matches a
member) is cross-checked so a renamed field fails here before it
ships stale docs.

The serving bench record is pinned likewise: its schema is
``profiling.SERVING_FIELDS`` (AST-read), every field must be
README-documented, and bench.py must build the record from the tuple.
The tree-serving bench (task_serving_tree) extends that record with
``profiling.TREE_SERVE_FIELDS``, pinned the same way.

The fleet summary block is pinned likewise: ``stats()["fleet"]`` from
serve/fleet.py and the bench.py task_fleet record are both
``profiling.FLEET_FIELDS``, every field must be README-documented,
and both builders must reference the tuple.

The ``dag`` block (every command routed through the pipeline DAG
scheduler) is pinned the same way: per-node records are
``profiling.DAG_FIELDS``, the summary is ``profiling.DAG_SUMMARY_FIELDS``,
every member must be README-documented, and the scheduler must build
its records from the tuple. Members of the pinned tuples are excluded
from the stage-field heuristic — `queue_s`/`wall_s`/... are dag-block
keys, not ``inputPipeline`` stages.

The ``trace`` block (attached to every step run with
``SHIFU_TPU_TRACE=1``) is pinned likewise: its schema is
``profiling.TRACE_FIELDS``, every member must be README-documented,
and obs/trace.py must build the block from the tuple.

The pod-scale data plane bench is pinned likewise: bench.py
task_dist_stats builds its record from ``profiling.SHARD_FIELDS``,
every member must be README-documented, and bench.py must reference
the tuple.

The continuous-refresh bench is pinned likewise: bench.py
task_refresh builds its record from ``profiling.REFRESH_FIELDS``,
every member must be README-documented (the Continuous refresh
section), and bench.py must reference the tuple.

The streaming-ingest bench is pinned likewise: bench.py task_ingest
builds its record from ``profiling.INGEST_FIELDS``, every member must
be README-documented (the Streaming ingest section), and bench.py
must reference the tuple.

The live-promotion bench is pinned likewise: bench.py task_canary
builds its record from ``profiling.CANARY_FIELDS``, every member must
be README-documented (the Live promotion section), and bench.py must
reference the tuple.

The health plane is pinned likewise: every metrics.jsonl point is
``profiling.METRIC_FIELDS`` (built by obs/health/store.py), every SLO
record is ``profiling.HEALTH_FIELDS`` (built by obs/health/slo.py),
every member must be README-documented, and both modules must
reference their tuple.

Optionally pass a real steps.jsonl to ALSO verify against a live log
(every documented field must appear in at least one record's
``inputPipeline`` block across the file, and any record carrying a
``roofline`` block must carry exactly the ROOFLINE_FIELDS keys):

    python tools/check_steps_schema.py [path/to/steps.jsonl]
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "shifu_tpu")
README = os.path.join(REPO, "README.md")

_TOKEN = re.compile(r"`([a-z][a-z0-9_]*(?:_s|_hits|_misses))`")
_WRITERS = ("add_stage_time", "add_stage_count")

# bench.py record keys that match the stage-token shape but are not
# steps.jsonl inputPipeline stages (like the per_s/_frac skips below)
_BENCH_ONLY = {"fanout_cache_misses"}


def documented_fields() -> set:
    with open(README, encoding="utf-8") as f:
        text = f.read()
    # members of the pinned block schemas (roofline/serving/dag) are
    # documented as those blocks' keys, not inputPipeline stages
    pinned = set(roofline_fields()) | set(serving_fields()) | \
        set(tree_serve_fields()) | set(fleet_fields()) | set(dag_fields()) | \
        set(dag_summary_fields()) | set(trace_fields()) | \
        set(metric_fields()) | set(health_fields()) | \
        set(shard_fields()) | set(refresh_fields()) | \
        set(ingest_fields()) | set(canary_fields()) | \
        set(slice_fields())
    return {tok for tok in _TOKEN.findall(text)
            if "per_s" not in tok and not tok.endswith("_frac")
            and tok not in pinned and tok not in _BENCH_ONLY}


def emitted_fields() -> set:
    out = set()
    for dirpath, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if isinstance(node, ast.Call):
                    fn = node.func
                    fname = fn.attr if isinstance(fn, ast.Attribute) \
                        else getattr(fn, "id", None)
                    if fname in _WRITERS and node.args and \
                            isinstance(node.args[0], ast.Constant) and \
                            isinstance(node.args[0].value, str):
                        out.add(node.args[0].value)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    # `stage="host_assemble_s"` style defaults name the
                    # emitted key when callers rely on the default
                    a = node.args
                    params = a.posonlyargs + a.args + a.kwonlyargs
                    defaults = ([None] * (len(a.posonlyargs + a.args)
                                          - len(a.defaults))
                                + list(a.defaults) + list(a.kw_defaults))
                    for p, d in zip(params, defaults):
                        if p.arg == "stage" and \
                                isinstance(d, ast.Constant) and \
                                isinstance(d.value, str):
                            out.add(d.value)
    return out


def _profiling_tuple(name: str) -> tuple:
    """A module-level tuple constant from profiling.py, read from the
    AST so this gate keeps working without importing jax-adjacent
    modules."""
    path = os.path.join(PKG, "profiling.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return tuple(ast.literal_eval(node.value))
    raise SystemExit(f"profiling.py no longer defines {name}")


def roofline_fields() -> tuple:
    return _profiling_tuple("ROOFLINE_FIELDS")


def serving_fields() -> tuple:
    return _profiling_tuple("SERVING_FIELDS")


def tree_serve_fields() -> tuple:
    return _profiling_tuple("TREE_SERVE_FIELDS")


def fleet_fields() -> tuple:
    return _profiling_tuple("FLEET_FIELDS")


def dag_fields() -> tuple:
    return _profiling_tuple("DAG_FIELDS")


def dag_summary_fields() -> tuple:
    return _profiling_tuple("DAG_SUMMARY_FIELDS")


def trace_fields() -> tuple:
    return _profiling_tuple("TRACE_FIELDS")


def metric_fields() -> tuple:
    return _profiling_tuple("METRIC_FIELDS")


def health_fields() -> tuple:
    return _profiling_tuple("HEALTH_FIELDS")


def shard_fields() -> tuple:
    return _profiling_tuple("SHARD_FIELDS")


def refresh_fields() -> tuple:
    return _profiling_tuple("REFRESH_FIELDS")


def ingest_fields() -> tuple:
    return _profiling_tuple("INGEST_FIELDS")


def canary_fields() -> tuple:
    return _profiling_tuple("CANARY_FIELDS")


def slice_fields() -> tuple:
    return _profiling_tuple("SLICE_FIELDS")


def check_roofline_docs() -> int:
    """Every ROOFLINE_FIELDS member must be backtick-documented in
    README (the Raw speed section) — a field added to the block without
    docs, or renamed out from under them, fails here."""
    fields = roofline_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("roofline schema drift: ROOFLINE_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    print(f"roofline block: all {len(fields)} ROOFLINE_FIELDS "
          "documented in README")
    return 0


def check_serving_docs() -> int:
    """Every SERVING_FIELDS member (bench.py task_serving's record
    schema) must be backtick-documented in README's Serving section,
    and task_serving must build its record from the tuple — the AST
    check asserts bench.py subscripts `profiling.SERVING_FIELDS` (or
    iterates it) so the record cannot silently drift from the pinned
    schema."""
    fields = serving_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("serving schema drift: SERVING_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    bench = os.path.join(REPO, "bench.py")
    with open(bench, encoding="utf-8") as f:
        uses = "SERVING_FIELDS" in f.read()
    if not uses:
        print("bench.py no longer builds the serving record from "
              "profiling.SERVING_FIELDS", file=sys.stderr)
        return 1
    print(f"serving bench: all {len(fields)} SERVING_FIELDS documented "
          "in README and pinned in bench.py")
    return 0


def check_tree_serve_docs() -> int:
    """Every TREE_SERVE_FIELDS member (the keys bench.py
    task_serving_tree adds on top of SERVING_FIELDS) must be
    backtick-documented in README, and task_serving_tree must build
    its record from the tuple — the literal check asserts bench.py
    references `TREE_SERVE_FIELDS` so the record cannot silently
    drift from the pinned schema."""
    fields = tree_serve_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("tree-serving schema drift: TREE_SERVE_FIELDS member(s) "
              f"never documented in README: {missing}", file=sys.stderr)
        return 1
    bench = os.path.join(REPO, "bench.py")
    with open(bench, encoding="utf-8") as f:
        uses = "TREE_SERVE_FIELDS" in f.read()
    if not uses:
        print("bench.py no longer builds the tree-serving record from "
              "profiling.TREE_SERVE_FIELDS", file=sys.stderr)
        return 1
    print(f"tree serving bench: all {len(fields)} TREE_SERVE_FIELDS "
          "documented in README and pinned in bench.py")
    return 0


def check_fleet_docs() -> int:
    """Every FLEET_FIELDS member (the ``stats()["fleet"]`` block and
    bench.py task_fleet's record schema) must be backtick-documented
    in README's Model fleet section, and both builders must construct
    their dicts from the tuple — the literal checks assert
    serve/fleet.py and bench.py reference `FLEET_FIELDS` so neither
    can silently drift from the pinned schema."""
    fields = fleet_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("fleet schema drift: FLEET_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    for path, what in ((os.path.join(PKG, "serve", "fleet.py"),
                        "shifu_tpu/serve/fleet.py"),
                       (os.path.join(REPO, "bench.py"), "bench.py")):
        with open(path, encoding="utf-8") as f:
            if "FLEET_FIELDS" not in f.read():
                print(f"{what} no longer builds the fleet block from "
                      "profiling.FLEET_FIELDS", file=sys.stderr)
                return 1
    print(f"model fleet: all {len(fields)} FLEET_FIELDS documented in "
          "README and pinned in serve/fleet.py + bench.py")
    return 0


def check_dag_docs() -> int:
    """Every DAG_FIELDS / DAG_SUMMARY_FIELDS member (the steps.jsonl
    ``dag`` block the scheduler attaches) must be backtick-documented
    in README's Pipeline DAG section, and the scheduler must build its
    per-node records from the tuple — the literal check asserts
    scheduler.py references `profiling.DAG_FIELDS` so the block cannot
    silently drift from the pinned schema."""
    fields = dag_fields() + dag_summary_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("dag schema drift: DAG_FIELDS/DAG_SUMMARY_FIELDS "
              f"member(s) never documented in README: {missing}",
              file=sys.stderr)
        return 1
    sched = os.path.join(PKG, "pipeline", "scheduler.py")
    with open(sched, encoding="utf-8") as f:
        uses = "DAG_FIELDS" in f.read()
    if not uses:
        print("pipeline/scheduler.py no longer builds the dag block "
              "from profiling.DAG_FIELDS", file=sys.stderr)
        return 1
    print(f"pipeline dag: all {len(fields)} DAG_FIELDS + "
          "DAG_SUMMARY_FIELDS documented in README and pinned in "
          "pipeline/scheduler.py")
    return 0


def check_trace_docs() -> int:
    """Every TRACE_FIELDS member (the steps.jsonl ``trace`` block the
    span tracer attaches) must be backtick-documented in README's
    Observability section, and obs/trace.py must build the block from
    the tuple — the literal check asserts trace.py references
    `TRACE_FIELDS` so the block cannot silently drift from the pinned
    schema."""
    fields = trace_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("trace schema drift: TRACE_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    tracer = os.path.join(PKG, "obs", "trace.py")
    with open(tracer, encoding="utf-8") as f:
        uses = "TRACE_FIELDS" in f.read()
    if not uses:
        print("obs/trace.py no longer builds the trace block from "
              "profiling.TRACE_FIELDS", file=sys.stderr)
        return 1
    print(f"trace plane: all {len(fields)} TRACE_FIELDS documented in "
          "README and pinned in obs/trace.py")
    return 0


def check_health_docs() -> int:
    """Every METRIC_FIELDS member (the metrics.jsonl point schema) and
    HEALTH_FIELDS member (the SLO evaluator's record schema) must be
    backtick-documented in README's Model health section, and the
    emitting modules must build their records from the tuples — the
    literal checks assert obs/health/store.py references METRIC_FIELDS
    and obs/health/slo.py references HEALTH_FIELDS so neither record
    can silently drift from its pinned schema."""
    fields = metric_fields() + health_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("health schema drift: METRIC_FIELDS/HEALTH_FIELDS "
              f"member(s) never documented in README: {missing}",
              file=sys.stderr)
        return 1
    for rel, tup in (("obs/health/store.py", "METRIC_FIELDS"),
                     ("obs/health/slo.py", "HEALTH_FIELDS")):
        path = os.path.join(PKG, *rel.split("/"))
        with open(path, encoding="utf-8") as f:
            if tup not in f.read():
                print(f"shifu_tpu/{rel} no longer builds its records "
                      f"from profiling.{tup}", file=sys.stderr)
                return 1
    print(f"health plane: all {len(fields)} METRIC_FIELDS + "
          "HEALTH_FIELDS documented in README and pinned in "
          "obs/health/store.py + obs/health/slo.py")
    return 0


def check_shard_docs() -> int:
    """Every SHARD_FIELDS member (bench.py task_dist_stats' record
    schema, the pod-scale data plane bench) must be backtick-documented
    in README's Pod-scale data plane section, and task_dist_stats must
    build its record from the tuple — the literal check asserts
    bench.py references `SHARD_FIELDS` so the record cannot silently
    drift from the pinned schema."""
    fields = shard_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("shard schema drift: SHARD_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    bench = os.path.join(REPO, "bench.py")
    with open(bench, encoding="utf-8") as f:
        uses = "SHARD_FIELDS" in f.read()
    if not uses:
        print("bench.py no longer builds the dist_stats record from "
              "profiling.SHARD_FIELDS", file=sys.stderr)
        return 1
    print(f"pod-scale data plane: all {len(fields)} SHARD_FIELDS "
          "documented in README and pinned in bench.py")
    return 0


def check_refresh_docs() -> int:
    """Every REFRESH_FIELDS member (bench.py task_refresh's record
    schema, the breach→promote closed-loop bench) must be
    backtick-documented in README's Continuous refresh section, and
    task_refresh must build its record from the tuple — the literal
    check asserts bench.py references `REFRESH_FIELDS` so the record
    cannot silently drift from the pinned schema."""
    fields = refresh_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("refresh schema drift: REFRESH_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    bench = os.path.join(REPO, "bench.py")
    with open(bench, encoding="utf-8") as f:
        uses = "REFRESH_FIELDS" in f.read()
    if not uses:
        print("bench.py no longer builds the refresh record from "
              "profiling.REFRESH_FIELDS", file=sys.stderr)
        return 1
    print(f"continuous refresh: all {len(fields)} REFRESH_FIELDS "
          "documented in README and pinned in bench.py")
    return 0


def check_ingest_docs() -> int:
    """Every INGEST_FIELDS member (bench.py task_ingest's record
    schema, the streaming row-log bench) must be backtick-documented
    in README's Streaming ingest section, and task_ingest must build
    its record from the tuple — the literal check asserts bench.py
    references `INGEST_FIELDS` so the record cannot silently drift
    from the pinned schema."""
    fields = ingest_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("ingest schema drift: INGEST_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    bench = os.path.join(REPO, "bench.py")
    with open(bench, encoding="utf-8") as f:
        uses = "INGEST_FIELDS" in f.read()
    if not uses:
        print("bench.py no longer builds the ingest record from "
              "profiling.INGEST_FIELDS", file=sys.stderr)
        return 1
    print(f"streaming ingest: all {len(fields)} INGEST_FIELDS "
          "documented in README and pinned in bench.py")
    return 0


def check_canary_docs() -> int:
    """Every CANARY_FIELDS member (bench.py task_canary's record
    schema, the live-promotion bench) must be backtick-documented in
    README's Live promotion section, and task_canary must build its
    record from the tuple — the literal check asserts bench.py
    references `CANARY_FIELDS` so the record cannot silently drift
    from the pinned schema."""
    fields = canary_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("canary schema drift: CANARY_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    bench = os.path.join(REPO, "bench.py")
    with open(bench, encoding="utf-8") as f:
        uses = "CANARY_FIELDS" in f.read()
    if not uses:
        print("bench.py no longer builds the canary record from "
              "profiling.CANARY_FIELDS", file=sys.stderr)
        return 1
    print(f"live promotion: all {len(fields)} CANARY_FIELDS "
          "documented in README and pinned in bench.py")
    return 0


def check_slice_docs() -> int:
    """Every SLICE_FIELDS member (bench.py task_pipeline's sliced-vs-
    timeshared A/B block) must be backtick-documented in README's
    Pipeline DAG section, and bench.py must build the block from the
    tuple — the literal check asserts bench.py references
    `SLICE_FIELDS` so the record cannot silently drift from the pinned
    schema."""
    fields = slice_fields()
    with open(README, encoding="utf-8") as f:
        documented = set(re.findall(r"`([a-z][a-z0-9_]*)`", f.read()))
    missing = sorted(set(fields) - documented)
    if missing:
        print("slice schema drift: SLICE_FIELDS member(s) never "
              f"documented in README: {missing}", file=sys.stderr)
        return 1
    bench = os.path.join(REPO, "bench.py")
    with open(bench, encoding="utf-8") as f:
        uses = "SLICE_FIELDS" in f.read()
    if not uses:
        print("bench.py no longer builds the slice A/B block from "
              "profiling.SLICE_FIELDS", file=sys.stderr)
        return 1
    print(f"slice A/B: all {len(fields)} SLICE_FIELDS documented in "
          "README and pinned in bench.py")
    return 0


def log_fields(path: str) -> set:
    out = set()
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            out |= set(rec.get("inputPipeline", {}))
    return out


def check_roofline_log(path: str) -> list:
    """Records carrying a ``roofline`` block must carry EXACTLY the
    ROOFLINE_FIELDS keys; returns the deviations (line no + diff)."""
    want = set(roofline_fields())
    bad = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            roof = rec.get("roofline")
            if not isinstance(roof, dict):
                continue
            got = set(roof)
            if got != want:
                bad.append(f"line {lineno}: missing="
                           f"{sorted(want - got)} extra={sorted(got - want)}")
    return bad


def main(argv) -> int:
    doc, emit = documented_fields(), emitted_fields()
    missing = sorted(doc - emit)
    if missing:
        print("steps.jsonl schema drift: README documents stage fields "
              "the code never emits:", file=sys.stderr)
        for tok in missing:
            print(f"  {tok}", file=sys.stderr)
        print(f"emitted vocabulary: {sorted(emit)}", file=sys.stderr)
        return 1
    print(f"steps.jsonl schema: {len(doc)} documented stage fields, "
          f"all within the {len(emit)}-key emitted vocabulary")
    if check_roofline_docs():
        return 1
    if check_serving_docs():
        return 1
    if check_tree_serve_docs():
        return 1
    if check_fleet_docs():
        return 1
    if check_dag_docs():
        return 1
    if check_trace_docs():
        return 1
    if check_health_docs():
        return 1
    if check_shard_docs():
        return 1
    if check_refresh_docs():
        return 1
    if check_ingest_docs():
        return 1
    if check_canary_docs():
        return 1
    if check_slice_docs():
        return 1
    if argv:
        seen = log_fields(argv[0])
        absent = sorted(doc - seen)
        if absent:
            print(f"live log {argv[0]} never carried documented "
                  f"field(s): {absent}", file=sys.stderr)
            return 1
        print(f"live log {argv[0]}: all documented fields observed")
        bad = check_roofline_log(argv[0])
        if bad:
            print(f"live log {argv[0]}: roofline block(s) deviate from "
                  f"ROOFLINE_FIELDS: {bad}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
