#!/usr/bin/env bash
# Repo lint gate — exits non-zero on ANY finding. Four passes:
#
#   1. `python -m shifu_tpu.analysis` over the package AND the
#      out-of-package knob readers (bench.py, tools/) — all sixteen
#      repo-native rules (see README "Static analysis" for the table),
#      including the whole-program concurrency/atomicity four:
#      raw-lock, thread-shared-mutation, non-atomic-write,
#      swallowed-exception. Runs with --timings and a 10s wall budget:
#      a rule that turns quadratic fails the gate loudly instead of
#      silently taxing every push (`--changed` exists for the
#      edit-loop; the gate always scans everything).
#   2. `python -m compileall` — syntax across every tree we ship.
#   3. hygiene: no tracked .pyc/__pycache__ artifacts, and the
#      fault-site registry must agree with the chaos matrix driver
#      (tools/chaos_sweep.sh enumerates resilience.FAULT_SITES, so a
#      site that import fails would silently shrink the sweep).
#   4. steps.jsonl schema: every stage field README documents must be
#      in the emitted vocabulary (tools/check_steps_schema.py).
#   5. ADVISORY (never fails lint): bench-history regression check
#      (tools/bench_regress.py) — BENCH_LOCAL.jsonl records are
#      hand-refreshed on hardware (the ROADMAP axon-probe caveat), so
#      findings here are printed for a human, not gated on.
#
# tests/test_lint.py runs pass 1 in tier-1; this script is the full
# pre-push/CI gate. Suppress an intentional finding inline with
#   # lint: disable=<rule> -- reason
#
# Usage: tools/lint.sh

set -u -o pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

rc=0

echo "== shifu_tpu.analysis (static rules) =="
python -m shifu_tpu.analysis shifu_tpu/ bench.py tools/ tests/synth.py \
  --timings --budget-s 10 \
  || rc=1

echo "== compileall (syntax) =="
python -m compileall -q shifu_tpu tools tests bench.py || rc=1

echo "== hygiene: tracked bytecode =="
TRACKED_PYC="$(git -C "$REPO" ls-files | grep -E '(\.pyc$|__pycache__/)' || true)"
if [ -n "$TRACKED_PYC" ]; then
  echo "tracked bytecode artifacts (git rm --cached them):" >&2
  echo "$TRACKED_PYC" >&2
  rc=1
else
  echo "clean"
fi

echo "== fault-site registry vs chaos matrix =="
python - <<'PYEOF' || rc=1
from shifu_tpu.resilience import FAULT_SITES

sites = list(FAULT_SITES)
dupes = {s for s in sites if sites.count(s) > 1}
assert not dupes, f"duplicate FAULT_SITES entries: {sorted(dupes)}"
assert sites, "FAULT_SITES is empty — the chaos matrix would be a no-op"
print(f"{len(sites)} fault sites registered; "
      "tools/chaos_sweep.sh sweeps all of them")
PYEOF

echo "== steps.jsonl schema (README vs emitted keys) =="
python tools/check_steps_schema.py || rc=1

echo "== bench regression (advisory — see ROADMAP perf-claim caveat) =="
python tools/bench_regress.py \
  || echo "bench_regress: findings above are ADVISORY (BENCH_LOCAL.jsonl is hand-refreshed on hardware); not failing lint"

if [ "$rc" -ne 0 ]; then
  echo "lint: FAILED" >&2
else
  echo "lint: OK"
fi
exit "$rc"
