"""Attribute the GBT end-to-end s/tree to its components on the real
backend (VERDICT r4 next #3): times, separately and under identical
11Mx28 shapes, (a) the full scanned boosting rounds, (b) the per-level
histogram kernel alone, (c) the row routing alone, (d) split selection
alone — each synced by a scalar fetch (block_until_ready is not a real
sync on the tunneled TPU). Appends one JSON line to
tools/profile_gbt.jsonl and optionally captures a jax.profiler trace
(SHIFU_TPU_GBT_TRACE=1 -> tools/gbt_trace/).

Usage: python tools/profile_gbt.py [rows] [trees]
"""
import json
import os

from shifu_tpu.config.environment import knob_bool, knob_raw
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 11_000_000
    trees = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    os.environ.setdefault("SHIFU_TPU_GBT_SCAN_GROUP", "5")
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the axon plugin pins jax_platforms via jax.config at
        # interpreter start, which OVERRIDES the env var — without this
        # a cpu-forced run still probes the (possibly wedged) tunnel
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from shifu_tpu.models import gbdt

    backend = jax.default_backend()
    n_bins = 64
    cols = 28
    depth = 6
    key = jax.random.PRNGKey(0)
    kb, kbeta, kn = jax.random.split(key, 3)
    binsT = jax.random.randint(kb, (cols, rows), 0, n_bins - 1,
                               dtype=jnp.int32)
    beta = jax.random.normal(kbeta, (cols,))
    margin = (beta @ binsT.astype(jnp.float32)) / np.sqrt(cols)
    y = (margin > jnp.median(margin)).astype(jnp.float32)
    w = jnp.ones(rows, jnp.float32)
    cfg = gbdt.TreeConfig(max_depth=depth, n_bins=n_bins,
                          learning_rate=0.2, loss="log")
    float(y[:4].sum())      # sync generation

    rec = {"ts": time.time(), "backend": backend, "rows": rows,
           "trees": trees, "depth": depth}

    def timed(name, fn, sync):
        fn()                                    # compile
        sync()
        t0 = time.time()
        fn()
        sync()
        rec[name] = round(time.time() - t0, 3)
        print(f"[profile] {name}: {rec[name]}s", file=sys.stderr,
              flush=True)

    # (a) full build
    out = {}

    def full():
        out["trees"], _ = gbdt.build_gbt(cfg, binsT, y, w, n_trees=trees)

    timed("full_build_s", full, lambda: None)   # build_gbt self-syncs
    rec["s_per_tree"] = round(rec["full_build_s"] / trees, 3)

    # component kernels at each level's realistic slot count. node ids
    # come from the REAL first tree's routing so occupancy is honest.
    tree0 = jax.tree.map(lambda a: jnp.asarray(a[0]), out["trees"])
    grad, hess = gbdt.gbt_gradients(y, jnp.zeros(rows), w, cfg.loss)

    node = jnp.zeros(rows, jnp.int32)
    nodes_per_level = [node]
    for d in range(depth):
        node = gbdt._route_level(cfg, tree0, binsT, node, d)
        nodes_per_level.append(node)

    # (b) histograms: every level's kernel, one jit, realistic slots
    @jax.jit
    def hists_all_levels(b, g, h):
        acc = 0.0
        for d in range(depth + 1):
            n_level = 2 ** d
            gh, hh = gbdt._level_histograms(
                b, nodes_per_level[min(d, depth)], g, h,
                2 ** d - 1, n_level, n_bins)
            acc = acc + gh.sum() + hh.sum()
        return acc

    timed("hist_levels_s",
          lambda: hists_all_levels(binsT, grad, hess),
          lambda: float(hists_all_levels(binsT, grad, hess)))

    # (c) routing: all levels' row advancement — both formulations
    # (env is read at trace time; tracing two distinct jits here keeps
    # the A/B inside one process)
    caller_route = knob_raw("SHIFU_TPU_GBT_ROUTE")
    for mode in ("gather", "onehot"):
        os.environ["SHIFU_TPU_GBT_ROUTE"] = mode

        # fresh function object per mode → its own jit cache; the env
        # is read at trace time inside _route_level
        @jax.jit
        def route_all(b):
            n = jnp.zeros(rows, jnp.int32)
            for d in range(depth):
                n = gbdt._route_level(cfg, tree0, b, n, d)
            return n.sum()

        timed(f"route_levels_{mode}_s", lambda: route_all(binsT),
              lambda: float(route_all(binsT)))  # lint: disable=host-sync-in-hot-loop -- profiling: scalar fetch defeats the tunnel's async no-op
    if caller_route is None:
        os.environ.pop("SHIFU_TPU_GBT_ROUTE", None)
    else:
        os.environ["SHIFU_TPU_GBT_ROUTE"] = caller_route

    # (d) split selection on depth-6-sized histograms (64 slots)
    g64 = jax.random.normal(key, (64, cols, n_bins))
    h64 = jnp.abs(jax.random.normal(kb, (64, cols, n_bins)))
    fm = jnp.ones(cols, jnp.float32)

    @jax.jit
    def splits(g, h):
        s = gbdt._best_splits((g, h), cfg, fm)
        return s["gain"].sum()

    timed("best_splits64_s", lambda: splits(g64, h64),
          lambda: float(splits(g64, h64)))

    # (e) gradient recompute + leaf gather (the boosting glue)
    @jax.jit
    def glue(pred):
        g, h = gbdt.gbt_gradients(y, pred, w, cfg.loss)
        contrib = tree0["leaf_value"][nodes_per_level[-1]]
        return (pred + cfg.learning_rate * contrib).sum() + g.sum() + h.sum()

    timed("glue_s", lambda: glue(jnp.zeros(rows)),
          lambda: float(glue(jnp.zeros(rows))))

    if knob_bool("SHIFU_TPU_GBT_TRACE"):
        import jax.profiler
        tdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "gbt_trace")
        with jax.profiler.trace(tdir):
            gbdt.build_gbt(cfg, binsT, y, w, n_trees=2)
        rec["trace_dir"] = tdir

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "profile_gbt.jsonl")
    with open(out_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
