#!/usr/bin/env bash
# Poll the axon tunnel; when a probe succeeds, re-measure the GBT
# ladder tasks live (the routing-reuse optimization changes their
# program) and commit the new records. Logs to tools/recapture_gbt.log.
set -u
cd "$(dirname "$0")/.."
LOG=tools/recapture_gbt.log
MAX_TRIES=${MAX_TRIES:-80}
SLEEP=${SLEEP:-150}

for i in $(seq 1 "$MAX_TRIES"); do
    echo "[recap $(date -u +%H:%M:%S)] probe $i" >> "$LOG"
    if timeout 120 python -c "import jax; assert jax.default_backend() == 'tpu'" >> "$LOG" 2>&1; then
        echo "[recap $(date -u +%H:%M:%S)] tunnel UP" >> "$LOG"
        before=$(wc -l < BENCH_LOCAL.jsonl)
        for task in gbt_small gbt; do
            echo "[recap $(date -u +%H:%M:%S)] task $task" >> "$LOG"
            timeout 1600 python tools/run_and_persist.py "$task" >> "$LOG" 2>&1
        done
        after=$(wc -l < BENCH_LOCAL.jsonl)
        if [ "$after" -gt "$before" ]; then
            git commit -q -m "Re-capture GBT TPU records after routing-reuse optimization

No-Verification-Needed: measurement-data-only commit (BENCH_LOCAL.jsonl)" \
                -- BENCH_LOCAL.jsonl
            echo "[recap] committed $((after - before)) record(s)" >> "$LOG"
            exit 0
        fi
        echo "[recap] no new records; will keep polling" >> "$LOG"
    fi
    sleep "$SLEEP"
done
echo "[recap] gave up after $MAX_TRIES probes" >> "$LOG"
exit 1
