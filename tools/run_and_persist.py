"""Run one bench ladder task in a subprocess and persist its record to
BENCH_LOCAL.jsonl exactly as the bench orchestrator would (`_persist`
with the workload stamp) — for targeted re-measurement of a single
task outside a full `python bench.py` run.

Usage: python tools/run_and_persist.py <task> [timeout_s]
Exits 0 only when the task produced a JSON record on a TPU backend.
"""
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def main():
    task = sys.argv[1]
    timeout = int(sys.argv[2]) if len(sys.argv) > 2 else 1500
    out, err = bench._run_task(task, timeout=timeout)
    if not out:
        print(f"[run_and_persist] {task} failed: {(err or '?')[-800:]}",
              file=sys.stderr)
        return 1
    backend = out.get("backend") or "tpu"
    if "backend" not in out:
        # ladder tasks don't self-report a backend; trust only a live
        # TPU probe so a CPU fallback can't masquerade as TPU evidence
        probe, _ = bench._run_task("probe", timeout=300)
        backend = (probe or {}).get("backend", "unknown")
    if backend != "tpu":
        print(f"[run_and_persist] backend was {backend}, not persisting"
              " as TPU evidence", file=sys.stderr)
        return 1
    bench._persist(task, backend,
                   {**out, "workload": bench._workload(task)})
    print(json.dumps({"persisted": task, **out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
