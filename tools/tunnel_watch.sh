#!/usr/bin/env bash
# Poll the axon TPU tunnel; the moment a probe succeeds, run the bench
# capture ladder (tools/capture_bench.sh commits records as they land).
# Logs to tools/tunnel_watch.log. Exits after a successful capture, or
# after MAX_TRIES probes.
set -u
cd "$(dirname "$0")/.."
LOG=tools/tunnel_watch.log
MAX_TRIES=${MAX_TRIES:-150}
SLEEP=${SLEEP:-120}

for i in $(seq 1 "$MAX_TRIES"); do
    echo "[watch $(date -u +%H:%M:%S)] probe $i" >> "$LOG"
    if timeout 90 python -c "import jax; assert jax.default_backend() != 'cpu'; print(jax.default_backend())" >> "$LOG" 2>&1; then
        echo "[watch $(date -u +%H:%M:%S)] tunnel UP — running capture" >> "$LOG"
        bash tools/capture_bench.sh >> "$LOG" 2>&1
        rc=$?
        echo "[watch $(date -u +%H:%M:%S)] capture exit=$rc" >> "$LOG"
        if [ "$rc" -eq 0 ]; then exit 0; fi
    fi
    sleep "$SLEEP"
done
echo "[watch] gave up after $MAX_TRIES probes" >> "$LOG"
exit 1
